"""Module — symbolic training over one or more devices.

Reference: `python/mxnet/module/module.py` — `bind` (:364) builds the
DataParallelExecutorGroup, `init_optimizer` (:474) decides
kvstore/update_on_kvstore via `model._create_kvstore`, `update`
(:644-662) routes through the kvstore or per-device updaters.
"""
from __future__ import annotations

import contextlib
import logging
import os
from typing import Any, Dict, List, Optional

from ..base import MXNetError
from ..context import Context, current_context
from ..initializer import InitDesc, Uniform
from ..io.io import DataDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint,
                     load_latest as _load_latest_checkpoint,
                     save_checkpoint)
from .. import health as _health
from .. import perf as _perf
from .. import resilience as _res
from ..ndarray.ndarray import NDArray, zeros
from .. import optimizer as opt_mod
from .base_module import BaseModule
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = [current_context()]
        if isinstance(context, Context):
            context = [context]
        self._context = list(context)
        self._monitor = None
        self._work_load_list = work_load_list
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._compression_params = compression_params

        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + \
            list(state_names or [])
        self._param_names = [n for n in arg_names if n not in input_names]
        self._fixed_param_names = list(fixed_param_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

        self._arg_params: Optional[Dict[str, NDArray]] = None
        self._aux_params: Optional[Dict[str, NDArray]] = None
        self._params_dirty = False

        self._exec_group: Optional[DataParallelExecutorGroup] = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Create a Module from a checkpoint (reference `module.py:149`)."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    @staticmethod
    def load_latest(prefix, load_optimizer_states=False, **kwargs):
        """Auto-resume: build a Module from the newest COMPLETE
        checkpoint under ``prefix`` (corrupt/partial ones are skipped
        via the CRC manifest — see `model.load_latest`).  Returns
        ``(module, epoch)``, or None when no restorable checkpoint
        exists (caller starts fresh)."""
        found = _load_latest_checkpoint(prefix)
        if found is None:
            return None
        sym, args, auxs, epoch = found
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod.params_initialized = True
        states = "%s-%04d.states" % (prefix, epoch)
        if load_optimizer_states and os.path.exists(states):
            mod._preload_opt_states = states
        return mod, epoch

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Atomic checkpoint (see `model.save_checkpoint`): params,
        symbol AND optimizer state land under one CRC manifest, so a
        crash mid-save never leaves a half-checkpoint that
        `load_latest` would trust."""
        self._sync_params_from_devices()
        states = self._optimizer_state_bytes() if save_optimizer_states \
            else None
        save_checkpoint(prefix, epoch, self.symbol, self._arg_params,
                        self._aux_params, states=states)

    def _optimizer_state_bytes(self):
        if not self.optimizer_initialized:
            raise MXNetError("init_optimizer() first")
        if self._update_on_kvstore:
            if self._kvstore._updater is None:
                raise MXNetError("kvstore has no updater to serialize")
            return self._kvstore._updater.get_states(dump_optimizer=False)
        return self._updater.get_states()

    # -- properties ---------------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        if not self.binded:
            raise MXNetError("not bound")
        return self._exec_group.data_shapes

    @property
    def label_shapes(self):
        if not self.binded:
            raise MXNetError("not bound")
        return self._exec_group.label_shapes

    @property
    def output_shapes(self):
        if not self.binded:
            raise MXNetError("not bound")
        shapes = self.symbol.infer_shape(
            **{d.name: d.shape for d in self.data_shapes})[1]
        return list(zip(self._output_names, shapes))

    # -- params -------------------------------------------------------------
    def get_params(self):
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        self._sync_params_from_devices()
        return (self._arg_params, self._aux_params)

    def _sync_params_from_devices(self):
        if self._params_dirty and self._exec_group is not None:
            self._exec_group.get_params(self._arg_params, self._aux_params)
            if self._kvstore is not None and self._update_on_kvstore:
                for name, arr in sorted(self._arg_params.items()):
                    try:
                        self._kvstore.pull(name, arr)
                    except MXNetError:
                        pass
            self._params_dirty = False

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("bind() first")
        if self._arg_params is None:
            self._arg_params = {
                name: zeros(arrs[0].shape, dtype=arrs[0].dtype)
                for name, arrs in zip(self._exec_group.param_names,
                                      self._exec_group.param_arrays)}
        if self._aux_params is None:
            self._aux_params = {
                name: zeros(arrs[0].shape, dtype=arrs[0].dtype)
                for name, arrs in zip(self._exec_group.aux_names,
                                      self._exec_group.aux_arrays)}

        def _impl(name, arr, cache):
            if cache is not None and name in cache:
                cache[name].copyto(arr)
            elif cache is not None and not allow_missing:
                raise MXNetError("%s not found in provided params" % name)
            elif initializer is not None:
                initializer(InitDesc(name, attrs=self.symbol.attr_dict()
                                     .get(name, {})), arr)

        attrs = {}
        for name, arr in sorted(self._arg_params.items()):
            _impl(name, arr, arg_params)
        for name, arr in sorted(self._aux_params.items()):
            _impl(name, arr, aux_params)
        self.params_initialized = True
        self._params_dirty = False
        self._exec_group.set_params(self._arg_params, self._aux_params,
                                    allow_extra=allow_extra)

    # -- bind ---------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._exec_group = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

        # mx.tune: with MXTPU_TUNE=apply, a persisted tuning config for
        # this graph (+ backend + batch profile) installs BEFORE the
        # executor group builds, so the knobs shape this bind's
        # programs.  Off (default) this is one bool check.
        from .. import tune as _tune

        if _tune.apply_enabled():
            _tune.maybe_apply(symbol=self._symbol,
                              profile=_tune.profile_of_shapes(data_shapes),
                              site="module.bind")

        shared_group = None
        if shared_module is not None:
            if not (shared_module.binded and
                    shared_module.params_initialized):
                raise MXNetError("shared_module must be bound+initialized")
            shared_group = shared_module._exec_group

        # mx.shard: Module is where the replica count becomes known, so
        # an ambient unpinned plan is resolved HERE — the shard pass
        # running under this bind stamps the real n onto the graph
        # (provenance shows "zero1:n=<replicas>", not a placeholder)
        from .. import sharding as _shard

        plan = _shard.current_plan()
        bind_scope = (
            _shard.plan_scope(plan.resolved(len(self._context)))
            if plan is not None and not plan.resolved_explicitly
            and len(self._context) > 1
            else contextlib.nullcontext())
        with bind_scope:
            self._exec_group = DataParallelExecutorGroup(
                self._symbol, self._context, self._work_load_list,
                data_shapes,
                label_shapes if for_training else (label_shapes or None),
                self._param_names, for_training, inputs_need_grad,
                shared_group, logger=self.logger,
                fixed_param_names=self._fixed_param_names,
                grad_req=grad_req)
        if shared_module is not None and shared_module.params_initialized:
            self._arg_params = shared_module._arg_params
            self._aux_params = shared_module._aux_params
            self.params_initialized = True
        elif self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    # -- optimizer ----------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Install the optimizer, routing updates through the kvstore
        when one is configured (reference `module.py` init_optimizer).

        `dist_sync` scales ``rescale_grad`` by the CONFIGURED worker
        count (``kvstore.num_workers``) and deliberately keeps it there
        under elastic membership: when a worker dies, sync rounds
        completed by the survivors are rescaled server-side by
        ``nw0/live`` (`docs/elastic.md`), so gradient averaging stays
        exact without rebinding or touching the optimizer — and a
        rejoining worker (``kvstore.rejoined``) slots back in with the
        identical rescale."""
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        if self.optimizer_initialized and not force_init:
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._arg_params)
        batch_size = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and \
                "_sync" in kvstore.type:
            batch_size *= kvstore.num_workers
        rescale_grad = 1.0 / batch_size

        # mx.shard: an active ShardingPlan (or MXTPU_SHARD=zero1) with
        # multiple replica contexts engages the ZeRO-1 sharded updater
        # — one updater, state in 1/N chunks — instead of N full
        # per-device updaters.  The plan owns the update PLACEMENT
        # too: in-process kvstores (local/device/tpu) drop to
        # aggregation-only so the sharded update runs here (the dist
        # PS keeps its server-side updates — sharding those is the
        # recsys item, ROADMAP 4).  The shard pass stamped the same
        # plan on the graph at bind.
        from .. import sharding as _shard

        plan = _shard.current_plan()
        zero1_possible = (plan is not None and len(self._context) > 1
                          and plan.shard_optimizer_state
                          and self._zero1_ok(optimizer))
        if zero1_possible and update_on_kvstore \
                and kvstore is not None and "dist" not in kvstore.type:
            update_on_kvstore = False
        use_zero1 = zero1_possible and not update_on_kvstore
        if use_zero1:
            plan = plan.resolved(len(self._context))

        idx2name = {}
        if update_on_kvstore or use_zero1:
            idx2name.update(enumerate(self._exec_group.param_names))
        else:
            for k in range(len(self._context)):
                idx2name.update(
                    {i * len(self._context) + k: n
                     for i, n in enumerate(self._exec_group.param_names)})

        if isinstance(optimizer, str):
            optimizer_params = dict(optimizer_params) if not \
                isinstance(optimizer_params, dict) else dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt_mod.create(optimizer,
                                       param_idx2name=idx2name,
                                       sym=self.symbol, **optimizer_params)
        else:
            if optimizer.rescale_grad != rescale_grad:
                self.logger.warning(
                    "Optimizer created manually outside Module but "
                    "rescale_grad != 1.0/batch_size (%s vs %s)",
                    optimizer.rescale_grad, rescale_grad)
            optimizer.idx2name = idx2name.copy()

        self._optimizer = optimizer
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        self._sharding_plan = plan if use_zero1 else None
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            _initialize_kvstore(kvstore=kvstore,
                                param_arrays=self._exec_group.param_arrays,
                                arg_params=self._arg_params,
                                param_names=self._exec_group.param_names,
                                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            kvstore.set_optimizer(self._optimizer)
        elif use_zero1:
            from ..sharding.zero1 import ZeRO1Updater

            self.logger.info("mx.shard: ZeRO-1 optimizer-state sharding "
                             "engaged (%s) over %d replicas",
                             plan.describe(), len(self._context))
            self._updater = ZeRO1Updater(optimizer, plan,
                                         idx2name=dict(idx2name))
        else:
            self._updater = opt_mod.get_updater(optimizer)
        self.optimizer_initialized = True
        if hasattr(self, "_preload_opt_states"):
            self.load_optimizer_states(self._preload_opt_states)
            del self._preload_opt_states

    @staticmethod
    def _zero1_ok(optimizer) -> bool:
        """Whether the (possibly not-yet-created) optimizer supports
        the elementwise-slicing contract of ZeRO-1."""
        if isinstance(optimizer, str):
            klass = opt_mod.Optimizer.opt_registry.get(optimizer.lower())
            return bool(klass is not None
                        and getattr(klass, "zero1_compatible", True))
        return bool(getattr(optimizer, "zero1_compatible", True))

    def borrow_optimizer(self, shared_module):
        """Share optimizer/kvstore/updater with another Module bound to
        the same parameters (BucketingModule, reference `module.py:604`)."""
        if not shared_module.optimizer_initialized:
            raise MXNetError("shared module has no optimizer")
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self._sharding_plan = getattr(shared_module, "_sharding_plan",
                                      None)
        self.optimizer_initialized = True

    # -- execution ----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        # re-bind on shape change (bucketing / last partial batch)
        curr_shapes = [d.shape for d in self._exec_group.data_shapes]
        new_shapes = [a.shape for a in data_batch.data]
        has_label = bool(getattr(data_batch, "label", None))
        # a labeled batch arriving while the bound exec group has no
        # label slots (e.g. after an unlabeled-batch rebind) must force
        # a rebind, or labels would silently never be copied in
        needs_label_rebind = (has_label and self.for_training
                              and not self._exec_group.label_shapes)
        effective_train = self.for_training if is_train is None else is_train
        if curr_shapes != new_shapes and not effective_train \
                and self._exec_group.can_forward_ragged(data_batch):
            # serving path: a ragged inference batch rides the
            # executor's shape-bucketed dispatch — the rebind below
            # would rebuild the executor and recompile per batch size.
            # A graph the bucketed dispatch can't serve (e.g. one that
            # combines a ragged input with a bound-shape arg the batch
            # didn't provide) falls through to the rebind path.
            try:
                self._exec_group.forward_ragged(data_batch)
                return
            except Exception:
                self.logger.debug("bucketed dispatch failed; rebinding",
                                  exc_info=True)
        if curr_shapes != new_shapes or needs_label_rebind:
            new_dshapes = [DataDesc(d.name, s) for d, s in
                           zip(self._exec_group.data_shapes, new_shapes)]
            new_lshapes = None
            if has_label:
                if self._exec_group.label_shapes:
                    new_lshapes = [DataDesc(l.name, a.shape) for l, a in
                                   zip(self._exec_group.label_shapes,
                                       data_batch.label)]
                else:
                    new_lshapes = [DataDesc(n, a.shape) for n, a in
                                   zip(self._label_names, data_batch.label)]
            elif self.for_training and self._exec_group.label_shapes:
                # unlabeled batch on a training module: keep the label
                # slots, scaled to the new batch size, so a later
                # labeled batch of this shape trains against fresh labels
                bs = new_shapes[0][0]
                new_lshapes = [DataDesc(l.name, (bs,) + tuple(l.shape[1:]))
                               for l in self._exec_group.label_shapes]
            self.reshape(new_dshapes, new_lshapes)
        self._exec_group.forward(data_batch, is_train)

    def reshape(self, data_shapes, label_shapes=None):
        # pull the freshest device weights into the host dicts first —
        # rebinding from stale host params would revert optimizer updates
        self._sync_params_from_devices()
        old_execs = set(map(id, self._exec_group.execs)) \
            if self._exec_group else set()
        arg_p, aux_p = self._arg_params, self._aux_params
        self.bind(data_shapes, label_shapes,
                  for_training=self.for_training,
                  inputs_need_grad=self.inputs_need_grad, force_rebind=True)
        if arg_p is not None:
            self._exec_group.set_params(arg_p, aux_p)
        if self._monitor is not None:
            # drop the discarded executors from the monitor before
            # installing the new group
            self._monitor.exes = [e for e in self._monitor.exes
                                  if id(e) not in old_execs]
            self._exec_group.install_monitor(self._monitor)

    def warmup(self):
        """AOT-compile the bound executors' programs
        (`Executor.warmup`): with the persistent compile cache enabled
        this turns the serving cold-start into cache deserialization,
        and the first real batch compiles nothing."""
        if not self.binded:
            raise MXNetError("bind() first")
        self._exec_group.warmup()
        return self

    def backward(self, out_grads=None):
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply optimizer using accumulated gradients (reference
        `module.py:644-662`)."""
        if not (self.binded and self.params_initialized and
                self.optimizer_initialized):
            raise MXNetError("init_optimizer() first")
        from .. import telemetry as _tel

        # deferred no-stall grad health on the Executor path; detection
        # re-executes the context the executor registered on its last
        # train dispatch.  Runs regardless of MXTPU_MAX_BAD_STEPS: the
        # Module path has no bad-step guard of its own (the Trainer /
        # FusedTrainLoop guards do not cover it), so arming the guard
        # must not silently turn monitoring OFF here.
        _health.monitor_grads("module", self._grad_vals)
        _health.maybe_stream_stats(self._stats_triple, site="module",
                                   scale=self._update_scale())
        self._params_dirty = True
        # perf phase attribution (mx.perf): the whole host-side update
        # segment — kvstore aggregation included — is the `optimizer`
        # phase of a Module step (the compiled fwd+bwd was accounted by
        # the Executor dispatch hook)
        pt0 = _perf.begin()
        if self._update_on_kvstore:
            _update_params_on_kvstore(self._exec_group.param_arrays,
                                      self._exec_group.grad_arrays,
                                      self._kvstore,
                                      self._exec_group.param_names)
        else:
            _update_params(self._exec_group.param_arrays,
                           self._exec_group.grad_arrays,
                           updater=self._updater,
                           num_device=len(self._context),
                           kvstore=self._kvstore,
                           param_names=self._exec_group.param_names)
        _perf.note_phase_since("optimizer", pt0)
        _tel.record_step(batch_size=self._exec_group.batch_size,
                         site="module")

    def _grad_vals(self):
        return [g._data
                for glist in self._exec_group.grad_arrays
                for g in glist if g is not None]

    def _update_scale(self) -> float:
        """lr x rescale_grad — makes the streamed update_ratio a real
        |Δw|/|w| estimate for plain SGD (best-effort; 1.0 when the
        optimizer hides its schedule)."""
        try:
            opt = self._optimizer
            lr = opt.lr if opt.lr_scheduler is None \
                else opt.lr_scheduler(opt.num_update)
            return abs(float(lr) * float(opt.rescale_grad))
        except Exception:
            return 1.0

    def _stats_triple(self):
        """(names, param vals, grad vals) for health stat streaming
        (first device replica)."""
        g = self._exec_group
        # param_arrays/grad_arrays skip param names absent from the
        # graph args — mirror that filter so the zip stays aligned
        pnames = [n for n in g.param_names if n in g.arg_names]
        names, ps, gs = [], [], []
        for name, parr, garr in zip(pnames, g.param_arrays,
                                    g.grad_arrays):
            if garr and garr[0] is not None:
                names.append(name)
                ps.append(parr[0]._data)
                gs.append(garr[0]._data)
        return names, ps, gs

    def get_outputs(self, merge_multi_context=True):
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        if not self.inputs_need_grad:
            raise MXNetError("bind with inputs_need_grad=True")
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._exec_group.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        if not self.binded:
            raise MXNetError("bind() first")
        self._monitor = mon
        self._exec_group.install_monitor(mon)

    # -- optimizer state ------------------------------------------------------
    def save_optimizer_states(self, fname):
        if not self.optimizer_initialized:
            raise MXNetError("init_optimizer() first")
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with _res.atomic_write(fname) as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if not self.optimizer_initialized:
            raise MXNetError("init_optimizer() first")
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())
