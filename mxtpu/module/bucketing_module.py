"""BucketingModule — per-bucket executors with shared parameters.

Reference: `python/mxnet/module/bucketing_module.py:36` — `sym_gen`
produces (symbol, data_names, label_names) per bucket key; executors are
bound lazily per bucket and share parameter storage with the default
bucket's module (`switch_bucket`, :322).

TPU note: each bucket is one whole-graph XLA module, so switching
buckets switches executables — same discipline as the reference's
per-bucket executors, but compilation is cached per shape signature.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, Optional

from ..base import MXNetError
from ..context import current_context
from ..initializer import Uniform
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key required")
        self._default_bucket_key = default_bucket_key
        self._sym_gen = sym_gen
        self._context = context if context is not None else current_context()
        self._work_load_list = work_load_list
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._compression_params = compression_params
        self._buckets: Dict[Any, Module] = {}
        self._curr_module: Optional[Module] = None
        self._curr_bucket_key = None
        self._monitor = None

    def _reset_bind(self):
        self.binded = False
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None

    def _gen_module(self, bucket_key):
        symbol, data_names, label_names = self._sym_gen(bucket_key)
        return Module(symbol, data_names, label_names, logger=self.logger,
                      context=self._context,
                      work_load_list=self._work_load_list,
                      fixed_param_names=self._fixed_param_names,
                      state_names=self._state_names,
                      compression_params=self._compression_params)

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        return self._sym_gen(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        return self._sym_gen(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        return self._curr_module.symbol

    def get_params(self):
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        # the default-bucket module owns the shared parameter storage
        self._curr_module._params_dirty = self._params_dirty
        params = self._curr_module.get_params()
        self._params_dirty = False
        return params

    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("bind() first")
        self._curr_module.init_params(initializer=initializer,
                                      arg_params=arg_params,
                                      aux_params=aux_params,
                                      allow_missing=allow_missing,
                                      force_init=force_init,
                                      allow_extra=allow_extra)
        self.params_initialized = True
        self._params_dirty = False

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if force_rebind:
            self._reset_bind()
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if shared_module is not None:
            raise MXNetError("shared_module unsupported for BucketingModule")
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True
        self._params_dirty = False

        module = self._gen_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training,
                    inputs_need_grad, force_rebind=False,
                    shared_module=None, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self._buckets[self._default_bucket_key] = module

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Bind (or reuse) the executor for `bucket_key` (reference
        `bucketing_module.py:322`); parameters are shared with the
        default bucket's module."""
        if not self.binded:
            raise MXNetError("bind() first")
        if bucket_key not in self._buckets:
            module = self._gen_module(bucket_key)
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad,
                        shared_module=self._buckets[
                            self._default_bucket_key])
            if self._monitor is not None:
                module.install_monitor(self._monitor)
            if self.optimizer_initialized:
                module.borrow_optimizer(
                    self._buckets[self._default_bucket_key])
            self._buckets[bucket_key] = module
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        if self.optimizer_initialized and not force_init:
            return
        self._buckets[self._default_bucket_key].init_optimizer(
            kvstore, optimizer, optimizer_params, force_init=force_init)
        for mod in self._buckets.values():
            if mod is not self._buckets[self._default_bucket_key]:
                mod.borrow_optimizer(self._buckets[self._default_bucket_key])
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        if not (self.binded and self.params_initialized):
            raise MXNetError("bind() and init_params() first")
        bucket_key = getattr(data_batch, "bucket_key",
                             self._default_bucket_key)
        self.switch_bucket(bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._params_dirty = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def install_monitor(self, mon):
        if not self.binded:
            raise MXNetError("bind() first")
        self._monitor = mon
        for mod in self._buckets.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        arg_p, aux_p = self.get_params()
        from ..model import save_checkpoint as _save

        _save(prefix, epoch,
              self._buckets[self._default_bucket_key].symbol, arg_p, aux_p)
