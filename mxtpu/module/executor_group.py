"""DataParallelExecutorGroup — per-device executors over a sliced batch.

Reference: `python/mxnet/module/executor_group.py:143` — the group owns
one Executor per context, slices each batch across devices by workload
(`decide_slices`, :281), runs forward (:436) / backward (:572), and
exposes param/grad arrays as [per-param][per-device] lists for the
kvstore update path.

TPU note: on a pod slice the idiomatic path is ONE sharded executor over
a mesh (`mxtpu.parallel`), not N executors; this group exists for the
reference's multi-context Module semantics and for the `kvstore=tpu`
per-key allreduce path, and degenerates to a single executor on one
context with zero overhead.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError
from ..context import Context
from ..executor import Executor
from ..io.io import DataDesc
from ..ndarray import ndarray as nd_mod
from ..ndarray.ndarray import NDArray

__all__ = ["DataParallelExecutorGroup", "_split_input_slice"]


def _split_input_slice(batch_size: int, work_load_list: Sequence[float]):
    """Split batch into per-device slices proportional to workload
    (reference `executor_manager.py:31`)."""
    total = sum(work_load_list)
    if total <= 0:
        raise MXNetError("invalid workload")
    slices = []
    begin = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = min(int(round(begin + batch_size * w / total)), batch_size)
        if end <= begin and batch_size >= len(work_load_list):
            raise MXNetError("too many slices for batch size %d" % batch_size)
        slices.append(slice(begin, end))
        begin = end
    return slices


def _desc_list(shapes):
    out = []
    for s in shapes or []:
        if isinstance(s, DataDesc):
            out.append(s)
        else:
            out.append(DataDesc(s[0], s[1]))
    return out


class DataParallelExecutorGroup(object):
    def __init__(self, symbol, contexts: List[Context],
                 workload: Optional[List[float]],
                 data_shapes, label_shapes, param_names: List[str],
                 for_training: bool, inputs_need_grad: bool,
                 shared_group: Optional["DataParallelExecutorGroup"] = None,
                 logger=logging, fixed_param_names=None, grad_req="write",
                 state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.workload = workload or [1.0] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = set(fixed_param_names or [])
        self.logger = logger
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.execs: List[Executor] = []
        self.data_shapes = _desc_list(data_shapes)
        self.label_shapes = _desc_list(label_shapes)
        self.data_names = [d.name for d in self.data_shapes]
        self.label_names = [l.name for l in self.label_shapes]
        self.batch_size = self.data_shapes[0].shape[0]
        self.slices = _split_input_slice(self.batch_size, self.workload)

        grad_req_dict: Dict[str, str] = {}
        for name in self.arg_names:
            if name in self.param_names:
                grad_req_dict[name] = "null" if not for_training or \
                    name in self.fixed_param_names else \
                    (grad_req if isinstance(grad_req, str)
                     else grad_req.get(name, "write"))
            elif name in self.data_names:
                grad_req_dict[name] = "write" if inputs_need_grad else "null"
            else:
                grad_req_dict[name] = "null"

        shared_execs = shared_group.execs if shared_group else None
        for i, ctx in enumerate(contexts):
            sl = self.slices[i]
            n = sl.stop - sl.start
            shape_kwargs = {}
            for d in self.data_shapes:
                shape_kwargs[d.name] = (n,) + tuple(d.shape[1:])
            for l in self.label_shapes:
                shape_kwargs[l.name] = (n,) + tuple(l.shape[1:])
            ex = symbol.simple_bind(ctx=ctx, grad_req=grad_req_dict,
                                    **shape_kwargs)
            if shared_execs is not None:
                # share parameter storage with the shared group's executor
                # on the same context (BucketingModule memory sharing,
                # reference executor_group.py shared_data_arrays)
                src = shared_execs[i]
                for name in self.param_names:
                    if name in src.arg_dict and name in ex.arg_dict:
                        ex.arg_dict[name] = src.arg_dict[name]
                        ex.arg_arrays[ex._arg_names.index(name)] = \
                            src.arg_dict[name]
                        gi = ex._arg_names.index(name)
                        src_grad = src.grad_arrays[
                            src._arg_names.index(name)]
                        if src_grad is not None:
                            ex.grad_arrays[gi] = src_grad
                            ex.grad_dict[name] = src_grad
                for name, arr in src.aux_dict.items():
                    if name in ex.aux_dict:
                        ex.aux_dict[name] = arr
                        ex.aux_arrays[ex._aux_names.index(name)] = arr
            self.execs.append(ex)

        # [per-param][per-device] views (reference param_arrays property)
        self.param_arrays = [[ex.arg_dict[name] for ex in self.execs]
                             for name in self.param_names
                             if name in self.arg_names]
        self.grad_arrays = [[ex.grad_dict.get(name) for ex in self.execs]
                            for name in self.param_names
                            if name in self.arg_names]
        self.aux_arrays = [[ex.aux_dict[name] for ex in self.execs]
                           for name in self.aux_names]

    # -- params -----------------------------------------------------------
    def set_params(self, arg_params, aux_params, allow_extra=False):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params,
                                allow_extra_params=allow_extra)

    def get_params(self, arg_params: Dict[str, NDArray],
                   aux_params: Dict[str, NDArray]):
        """Average per-device copies into the given dicts (reference
        `executor_group.py:400`)."""
        for name, blocks in zip(self.param_names, self.param_arrays):
            weight = blocks[0]
            if len(blocks) > 1:
                acc = blocks[0].copyto(blocks[0].ctx)
                for b in blocks[1:]:
                    acc += b.as_in_context(acc.ctx)
                weight = acc / len(blocks)
            arg_params[name] = weight.copyto(weight.ctx)
        for name, blocks in zip(self.aux_names, self.aux_arrays):
            weight = blocks[0]
            if len(blocks) > 1:
                acc = blocks[0].copyto(blocks[0].ctx)
                for b in blocks[1:]:
                    acc += b.as_in_context(acc.ctx)
                weight = acc / len(blocks)
            aux_params[name] = weight.copyto(weight.ctx)

    # -- execution --------------------------------------------------------
    def _slice_to(self, arrays, names):
        """Scatter host batch arrays into each executor's bound args."""
        for name, arr in zip(names, arrays):
            for ex, sl in zip(self.execs, self.slices):
                if name not in ex.arg_dict:
                    continue
                dst = ex.arg_dict[name]
                src = arr[sl.start:sl.stop] if arr.shape[0] != \
                    (sl.stop - sl.start) or len(self.execs) > 1 else arr
                if src.ctx != dst.ctx:
                    src = src.as_in_context(dst.ctx)
                dst._set_jax(src._data.astype(dst.dtype)
                             if src.dtype != dst.dtype else src._data)

    def forward(self, data_batch, is_train: Optional[bool] = None):
        if is_train is None:
            is_train = self.for_training
        self._slice_to(data_batch.data, self.data_names)
        if self.label_shapes and getattr(data_batch, "label", None):
            self._slice_to(data_batch.label, self.label_names)
        for ex in self.execs:
            ex.forward(is_train=is_train)

    def _ragged_slots(self, data_batch):
        """(name, array) pairs the ragged dispatch would feed: the data
        slots plus the label slots when the batch carries labels (so a
        label-consuming graph sees THIS batch's labels, not the stale
        bound ones)."""
        pairs = list(zip(self.data_names, data_batch.data))
        labels = getattr(data_batch, "label", None)
        if labels:
            pairs += list(zip(self.label_names, labels))
        return pairs

    def can_forward_ragged(self, data_batch) -> bool:
        """Whether a batch whose leading dim differs from the bound
        shapes can be served through the executor's shape-bucketed
        inference dispatch instead of a full rebind: single executor,
        bucketing on, and every data/label slot sharing ONE leading
        batch dim with trailing dims matching the bound shapes."""
        from .. import compile_cache as _cc

        if len(self.execs) != 1 or not _cc.bucketing_enabled():
            return False
        ex = self.execs[0]
        leading = set()
        for name, arr in self._ragged_slots(data_batch):
            if name not in ex.arg_dict:
                return False
            bound = ex.arg_dict[name].shape
            if len(arr.shape) != len(bound) or \
                    tuple(arr.shape[1:]) != tuple(bound[1:]) or \
                    len(arr.shape) == 0:
                return False
            leading.add(arr.shape[0])
        return len(leading) == 1

    def forward_ragged(self, data_batch):
        """Single-executor inference over a ragged batch: the executor
        pads the leading dim up to the active bucket and slices the
        outputs back — no rebind, no per-shape compile (see
        `mxtpu/compile_cache.py`)."""
        ex = self.execs[0]
        kwargs = {}
        for name, arr in self._ragged_slots(data_batch):
            kwargs[name] = arr if isinstance(arr, NDArray) \
                else NDArray(arr, ctx=ex._ctx)
        ex.forward(is_train=False, **kwargs)

    def warmup(self):
        """AOT-compile every executor's programs (Executor.warmup)."""
        for ex in self.execs:
            ex.warmup()

    def backward(self, out_grads=None):
        if not self.for_training:
            raise MXNetError("re-bind with for_training=True to backward")
        for i, ex in enumerate(self.execs):
            og = None
            if out_grads is not None:
                og = [g[self.slices[i].start:self.slices[i].stop]
                      for g in out_grads]
            ex.backward(out_grads=og)

    def get_outputs(self, merge_multi_context: bool = True):
        if merge_multi_context and len(self.execs) > 1:
            merged = []
            for oi in range(len(self.execs[0].outputs)):
                parts = [ex.outputs[oi] for ex in self.execs]
                ctx0 = parts[0].ctx
                parts = [p.as_in_context(ctx0) for p in parts]
                merged.append(nd_mod.concat(*parts, dim=0))
            return merged
        if len(self.execs) == 1:
            return list(self.execs[0].outputs)
        return [[ex.outputs[oi] for ex in self.execs]
                for oi in range(len(self.execs[0].outputs))]

    def get_input_grads(self, merge_multi_context: bool = True):
        grads = []
        for name in self.data_names:
            parts = [ex.grad_dict.get(name) for ex in self.execs]
            if merge_multi_context and len(parts) > 1:
                ctx0 = parts[0].ctx
                grads.append(nd_mod.concat(
                    *[p.as_in_context(ctx0) for p in parts], dim=0))
            else:
                grads.append(parts[0] if len(parts) == 1 else parts)
        return grads

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        for i, (ex, sl) in enumerate(zip(self.execs, self.slices)):
            labels_slice = []
            for label in (labels[i] if pre_sliced else labels):
                labels_slice.append(label if pre_sliced
                                    else label[sl.start:sl.stop])
            eval_metric.update(labels_slice, list(ex.outputs))

    def install_monitor(self, mon):
        for ex in self.execs:
            mon.install(ex)
