"""Pretrained token embeddings (reference
`python/mxnet/contrib/text/embedding.py`).

A `_TokenEmbedding` is a Vocabulary plus an (N, dim) vector table held
as an `mxtpu` NDArray.  The reference downloads GloVe/fastText files on
demand; this build runs with zero egress, so the named formats load
from a local ``embedding_root`` directory (same file names the
reference would download, e.g. ``glove.6B.50d.txt``) and raise a clear
error when the file is absent.  `CustomEmbedding` loads any
word-per-line text file; `CompositeEmbedding` concatenates several
tables over one vocabulary.
"""
from __future__ import annotations

import io
import logging
import os
from typing import Dict, List, Optional

import numpy as np

from ...ndarray.ndarray import NDArray, array as nd_array
from . import vocab as _vocab

__all__ = ["register", "create", "get_pretrained_file_names",
           "GloVe", "FastText", "CustomEmbedding", "CompositeEmbedding"]

_REGISTRY: Dict[str, type] = {}


def register(embedding_cls):
    """Register a `_TokenEmbedding` subclass under its lowercase class
    name (reference embedding.register)."""
    _REGISTRY[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding by name (reference
    embedding.create)."""
    name = embedding_name.lower()
    if name not in _REGISTRY:
        raise KeyError("unknown embedding %r (registered: %s)"
                       % (embedding_name, sorted(_REGISTRY)))
    return _REGISTRY[name](**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names per embedding (reference
    get_pretrained_file_names)."""
    if embedding_name is not None:
        cls = _REGISTRY[embedding_name.lower()]
        return list(cls.pretrained_file_names)
    return {n: list(c.pretrained_file_names)
            for n, c in _REGISTRY.items()}


class _TokenEmbedding(_vocab.Vocabulary):
    """Vocabulary + vector table.  Subclasses set the pretrained file
    inventory; loading parses ``token<delim>v1<delim>...vD`` lines."""

    pretrained_file_names: tuple = ()

    def __init__(self, **kwargs):
        super(_TokenEmbedding, self).__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec: Optional[NDArray] = None

    # -- loading ----------------------------------------------------------
    def _load_embedding(self, path, elem_delim, init_unknown_vec,
                        encoding="utf8"):
        if not os.path.isfile(path):
            raise OSError(
                "pretrained embedding file %r not found. This build has "
                "no network egress: place the file there manually (the "
                "reference would download it)" % path)
        loaded: Dict[str, np.ndarray] = {}
        vec_len = None

        def _is_header(parts):
            # fastText header: exactly two integer fields ("N dim")
            if len(parts) != 2:
                return False
            try:
                int(parts[0]), int(parts[1])
                return True
            except ValueError:
                return False

        with io.open(path, "r", encoding=encoding) as f:
            for lineno, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if lineno == 0 and _is_header(parts):
                    continue
                if len(parts) < 2:
                    logging.getLogger(__name__).warning(
                        "skipping malformed line %d of %s", lineno, path)
                    continue
                token, elems = parts[0], parts[1:]
                if vec_len is None:
                    vec_len = len(elems)
                elif len(elems) != vec_len:
                    logging.getLogger(__name__).warning(
                        "line %d of %s has %d elems (expected %d) — "
                        "skipped", lineno, path, len(elems), vec_len)
                    continue
                if token in loaded:
                    continue  # first occurrence wins (reference)
                if token not in self._token_to_idx:
                    self._token_to_idx[token] = len(self._idx_to_token)
                    self._idx_to_token.append(token)
                loaded[token] = np.asarray(elems, np.float32)
        if vec_len is None:
            raise ValueError("no vectors found in %r" % path)
        self._vec_len = vec_len
        # fill by token so pre-indexed tokens (a Vocabulary counter, the
        # unknown token appearing in the file) get their file vectors;
        # indexed tokens ABSENT from the file get the unknown vector,
        # consistent with index 0 and with _from_vocabulary
        unk = np.asarray(loaded.get(self._unknown_token,
                                    init_unknown_vec(vec_len)), np.float32)
        table = np.tile(unk, (len(self._idx_to_token), 1))
        for token, vec in loaded.items():
            table[self._token_to_idx[token]] = vec
        table[0] = unk
        self._idx_to_vec = nd_array(table)

    # -- queries ----------------------------------------------------------
    @property
    def vec_len(self) -> int:
        return self._vec_len

    @property
    def idx_to_vec(self) -> Optional[NDArray]:
        return self._idx_to_vec

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Vectors for token(s); unknown tokens get the unknown vector
        (optionally retrying lower-cased)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            i = self._token_to_idx.get(t, 0)
            if i == 0 and lower_case_backup:
                i = self._token_to_idx.get(t.lower(), 0)
            idxs.append(i)
        table = self._idx_to_vec.asnumpy()
        out = table[np.asarray(idxs, np.int64)]
        return nd_array(out[0] if single else out)

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite vectors for known tokens (reference
        update_token_vectors; unknown tokens raise)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        vecs = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else np.asarray(new_vectors, np.float32)
        if single or vecs.ndim == 1:
            vecs = vecs.reshape(1, -1)
        table = np.array(self._idx_to_vec.asnumpy())  # asnumpy is read-only
        for t, v in zip(toks, vecs):
            if t not in self._token_to_idx:
                raise ValueError("token %r is not in the embedding "
                                 "vocabulary" % t)
            table[self._token_to_idx[t]] = v
        self._idx_to_vec = nd_array(table)

    # -- vocabulary-restricted build (reference
    #    _build_embedding_for_vocabulary) ---------------------------------
    @classmethod
    def _from_vocabulary(cls, vocabulary, source):
        emb = _TokenEmbedding.__new__(_TokenEmbedding)
        _vocab.Vocabulary.__init__(
            emb, unknown_token=vocabulary.unknown_token,
            reserved_tokens=vocabulary.reserved_tokens)
        emb._idx_to_token = list(vocabulary.idx_to_token)
        emb._token_to_idx = dict(vocabulary.token_to_idx)
        emb._vec_len = source.vec_len
        src_table = source.idx_to_vec.asnumpy()
        rows = np.asarray([source.token_to_idx.get(t, 0)
                           for t in emb._idx_to_token], np.int64)
        emb._idx_to_vec = nd_array(src_table[rows])
        return emb


def _default_embedding_root():
    return os.environ.get(
        "MXTPU_EMBEDDING_ROOT",
        os.path.join(os.path.expanduser("~"), ".mxtpu", "embedding"))


@register
class GloVe(_TokenEmbedding):
    """GloVe vectors (reference contrib.text.embedding.GloVe); loads
    ``<embedding_root>/glove/<pretrained_file_name>``."""

    pretrained_file_names = (
        "glove.42B.300d.txt", "glove.6B.50d.txt", "glove.6B.100d.txt",
        "glove.6B.200d.txt", "glove.6B.300d.txt", "glove.840B.300d.txt",
        "glove.twitter.27B.25d.txt", "glove.twitter.27B.50d.txt",
        "glove.twitter.27B.100d.txt", "glove.twitter.27B.200d.txt")

    def __init__(self, pretrained_file_name="glove.840B.300d.txt",
                 embedding_root=None, init_unknown_vec=np.zeros, **kwargs):
        super(GloVe, self).__init__(**kwargs)
        root = embedding_root or _default_embedding_root()
        self._load_embedding(
            os.path.join(root, "glove", pretrained_file_name), " ",
            init_unknown_vec)


@register
class FastText(_TokenEmbedding):
    """fastText vectors (reference contrib.text.embedding.FastText);
    loads ``<embedding_root>/fasttext/<pretrained_file_name>``."""

    pretrained_file_names = (
        "wiki.en.vec", "wiki.simple.vec", "wiki.zh.vec", "wiki.fr.vec",
        "wiki.de.vec", "wiki.es.vec", "wiki.ja.vec", "wiki.ru.vec")

    def __init__(self, pretrained_file_name="wiki.simple.vec",
                 embedding_root=None, init_unknown_vec=np.zeros, **kwargs):
        super(FastText, self).__init__(**kwargs)
        root = embedding_root or _default_embedding_root()
        self._load_embedding(
            os.path.join(root, "fasttext", pretrained_file_name), " ",
            init_unknown_vec)


@register
class CustomEmbedding(_TokenEmbedding):
    """Embedding from any local ``token<delim>v...`` text file
    (reference CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", init_unknown_vec=np.zeros, **kwargs):
        super(CustomEmbedding, self).__init__(**kwargs)
        self._load_embedding(pretrained_file_path, elem_delim,
                             init_unknown_vec, encoding=encoding)


class CompositeEmbedding(_TokenEmbedding):
    """Concatenation of several token embeddings over one vocabulary
    (reference CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings):
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        _vocab.Vocabulary.__init__(
            self, unknown_token=vocabulary.unknown_token,
            reserved_tokens=vocabulary.reserved_tokens)
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        parts = [_TokenEmbedding._from_vocabulary(vocabulary, e)
                 for e in token_embeddings]
        self._vec_len = sum(p.vec_len for p in parts)
        self._idx_to_vec = nd_array(np.concatenate(
            [p.idx_to_vec.asnumpy() for p in parts], axis=1))
