"""Text token indexing (reference `python/mxnet/contrib/text/vocab.py`).

A `Vocabulary` maps tokens to contiguous integer indices.  Index 0 is
the unknown token; user-supplied reserved tokens follow; remaining
slots are filled from a frequency counter, most-frequent first with
ties broken lexically — the reference's ordering contract, kept so
index assignments match across the two frameworks.
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence

__all__ = ["Vocabulary"]


class Vocabulary(object):
    """Token index built from a `collections.Counter`.

    Parameters
    ----------
    counter : token frequencies; None builds a vocabulary holding only
        the unknown + reserved tokens.
    most_freq_count : cap on the number of counter-derived tokens.
    min_freq : minimum frequency for a counter token to be indexed.
    unknown_token : representation for out-of-vocabulary tokens
        (always index 0).
    reserved_tokens : tokens guaranteed an index (e.g. padding/BOS);
        must not duplicate each other or the unknown token.
    """

    def __init__(self, counter: Optional[Counter] = None,
                 most_freq_count: Optional[int] = None, min_freq: int = 1,
                 unknown_token: str = "<unk>",
                 reserved_tokens: Optional[Sequence[str]] = None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        if reserved_tokens is not None:
            rset = set(reserved_tokens)
            if len(rset) != len(reserved_tokens):
                raise ValueError("reserved_tokens must not contain "
                                 "duplicates")
            if unknown_token in rset:
                raise ValueError("reserved_tokens must not contain the "
                                 "unknown token")
        self._unknown_token = unknown_token
        self._reserved_tokens = (list(reserved_tokens)
                                 if reserved_tokens else None)
        self._idx_to_token: List[str] = [unknown_token] + \
            (list(reserved_tokens) if reserved_tokens else [])
        self._token_to_idx: Dict[str, int] = {
            t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            self._index_counter_keys(counter, most_freq_count, min_freq)

    def _index_counter_keys(self, counter, most_freq_count, min_freq):
        # most-frequent first, ties lexical (reference ordering)
        pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
        taken = 0
        for token, freq in pairs:
            if freq < min_freq:
                break
            if most_freq_count is not None and taken >= most_freq_count:
                break
            if token not in self._token_to_idx:
                self._token_to_idx[token] = len(self._idx_to_token)
                self._idx_to_token.append(token)
                taken += 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self) -> Dict[str, int]:
        return self._token_to_idx

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def unknown_token(self) -> str:
        return self._unknown_token

    @property
    def reserved_tokens(self) -> Optional[List[str]]:
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) -> index(es); unknown tokens map to index 0."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        """Index(es) -> token(s); out-of-range raises ValueError."""
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        out = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError("token index %d out of range [0, %d)"
                                 % (i, len(self._idx_to_token)))
            out.append(self._idx_to_token[i])
        return out[0] if single else out
