"""TensorBoard metric logging (reference
`python/mxnet/contrib/tensorboard.py`).

The reference's `LogMetricsCallback` delegates to the external
``tensorboard`` package's SummaryWriter.  This build has no external
dependency: `SummaryWriter` below writes genuine TensorBoard event
files (TFRecord framing with masked CRC32C + hand-encoded
``tensorflow.Event`` protos for scalar summaries), so the output
directory loads in stock TensorBoard.  Only scalars are supported —
exactly what the reference callback emits.
"""
from __future__ import annotations

import os
import struct
import time

__all__ = ["SummaryWriter", "LogMetricsCallback"]


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli, reflected poly 0x82F63B78) — required by the
# TFRecord framing; table-based, pure python.
# ---------------------------------------------------------------------------

def _crc32c_table():
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
        table.append(crc)
    return table


_TABLE = _crc32c_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Minimal protobuf wire encoding for tensorflow.Event scalar summaries
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _len_delim(field: int, payload: bytes) -> bytes:
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _scalar_summary(tag: str, value: float) -> bytes:
    # Summary.Value: tag (field 1, string) + simple_value (field 2, float)
    val = _len_delim(1, tag.encode("utf8")) + \
        _varint((2 << 3) | 5) + struct.pack("<f", value)
    # Summary: repeated value (field 1, message)
    return _len_delim(1, val)


def _event(wall_time: float, step: int, *, file_version: str = None,
           summary: bytes = None) -> bytes:
    out = _varint((1 << 3) | 1) + struct.pack("<d", wall_time)
    out += _varint((2 << 3) | 0) + _varint(step & 0xFFFFFFFFFFFFFFFF)
    if file_version is not None:
        out += _len_delim(3, file_version.encode("utf8"))
    if summary is not None:
        out += _len_delim(5, summary)
    return out


class SummaryWriter(object):
    """Scalar-only TensorBoard event writer (stand-in for the external
    package's SummaryWriter; event files load in stock TensorBoard)."""

    _instance_counter = 0

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        # pid + per-process counter keep concurrent writers on one
        # logdir from truncating each other (the reference appends
        # hostname + pid the same way)
        SummaryWriter._instance_counter += 1
        fname = "events.out.tfevents.%d.%d.%d.mxtpu" % (
            int(time.time()), os.getpid(), SummaryWriter._instance_counter)
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "wb")
        self._write_record(_event(time.time(), 0,
                                  file_version="brain.Event:2"))

    def _write_record(self, data: bytes):
        header = struct.pack("<Q", len(data))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))

    def add_scalar(self, tag: str, value: float, global_step: int = 0):
        self._write_record(_event(time.time(), int(global_step),
                                  summary=_scalar_summary(tag,
                                                          float(value))))

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class LogMetricsCallback(object):
    """Batch-end callback streaming metric values to TensorBoard
    (reference contrib.tensorboard.LogMetricsCallback).

    ::

        tb = mx.contrib.tensorboard.LogMetricsCallback('logs/train')
        mod.fit(train_iter, num_epoch=2, batch_end_callback=tb)
    """

    def __init__(self, logging_dir: str, prefix: str = None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self.step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
        self.summary_writer.flush()
