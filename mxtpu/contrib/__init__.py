"""Contrib: experimental / interchange subsystems (reference
`python/mxnet/contrib/`): INT8 quantization calibration, ONNX
interchange, text embeddings, SVRG optimization, TensorBoard logging."""
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import text  # noqa: F401
from . import svrg_optimization  # noqa: F401
from . import tensorboard  # noqa: F401
