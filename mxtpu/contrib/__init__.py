"""Contrib: experimental / interchange subsystems (reference
`python/mxnet/contrib/`): INT8 quantization calibration + ONNX."""
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
