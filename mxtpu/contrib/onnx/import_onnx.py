"""ONNX -> Symbol import (reference `contrib/onnx/onnx2mx/import_model.py`).

Parses a ModelProto (via `_proto.py`) and rebuilds the graph with
mxtpu symbols; initializers become arg_params (BatchNormalization's
running mean/var become aux_params, matching the reference's aux
split).  Covers the same op subset the exporter emits.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np

from ...base import MXNetError
from ...ndarray.ndarray import array as nd_array
from ...symbol.register import invoke_symbol
from ...symbol.symbol import Symbol, Variable
from . import _proto as P

_NP_DT = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
          7: np.int64, 9: np.bool_, 11: np.float64}

_ACT = {"Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
        "Softplus": "softrelu", "Softsign": "softsign"}
_ELEMWISE = {"Add": "broadcast_add", "Mul": "broadcast_mul",
             "Sub": "broadcast_sub", "Div": "broadcast_div"}
_UNARY = {"Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Abs": "abs",
          "Neg": "negative", "Floor": "floor", "Ceil": "ceil",
          "Identity": "_copy"}


def _parse_tensor(raw: bytes) -> Tuple[str, np.ndarray]:
    f = P.parse(raw)
    dims: List[int] = []
    for wire, v in f.get(1, []):  # proto3 packs repeated int64 (wire 2)
        dims.extend(P.unpack_ints(v) if wire == 2 else [v])
    dtype = _NP_DT[P.first(f, 2, 1)]
    name = P.as_str(P.first(f, 8))
    if 9 in f:
        arr = np.frombuffer(P.first(f, 9), dtype=dtype).reshape(dims)
    elif 4 in f:  # float_data
        arr = np.asarray(P.every(f, 4), np.float32).reshape(dims)
    elif 7 in f:  # int64_data (possibly packed)
        vals = []
        for wire, v in f[7]:
            vals.extend(P.unpack_ints(v) if wire == 2 else [v])
        arr = np.asarray(vals, np.int64).reshape(dims)
    else:
        arr = np.zeros(dims, dtype)
    return name, arr


def _parse_attrs(node_fields) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for raw in P.every(node_fields, 5):
        f = P.parse(raw)
        name = P.as_str(P.first(f, 1))
        atype = P.first(f, 20, 0)
        if atype == 1:
            out[name] = P.first(f, 2)
        elif atype == 2:
            out[name] = P.first(f, 3)
        elif atype == 3:
            out[name] = P.as_str(P.first(f, 4))
        elif atype == 4:
            out[name] = _parse_tensor(P.first(f, 5))[1]
        elif atype == 7:
            vals = []
            for wire, v in f.get(8, []):
                vals.extend(P.unpack_ints(v) if wire == 2 else [v])
            out[name] = tuple(vals)
        elif atype == 6:
            vals = []
            for wire, v in f.get(7, []):
                if wire == 2:  # packed fixed32 floats
                    vals.extend(struct.unpack("<%df" % (len(v) // 4), v))
                else:
                    vals.append(v)
            out[name] = tuple(vals)
    return out


def _pairs(t, n=2, default=1):
    t = tuple(int(x) for x in (t or ()))
    return t[:n] if t else (default,) * n


def import_model(onnx_file_path: str):
    """Load an ONNX file -> (sym, arg_params, aux_params)
    (reference `onnx_mxnet.import_model`)."""
    with open(onnx_file_path, "rb") as f:
        model = P.parse(f.read())
    graph = P.parse(P.first(model, 7, b""))

    inits: Dict[str, np.ndarray] = {}
    for raw in P.every(graph, 5):
        name, arr = _parse_tensor(raw)
        inits[name] = arr

    tensors: Dict[str, Symbol] = {}
    for raw in P.every(graph, 11):  # graph inputs
        fi = P.parse(raw)
        name = P.as_str(P.first(fi, 1))
        if name not in inits:
            tensors[name] = Variable(name)

    arg_params: Dict[str, Any] = {}
    aux_names: set = set()
    consumed: set = set()  # initializers folded into attrs (not params)

    def sym_in(name) -> Symbol:
        if name not in tensors:
            if name in inits:
                tensors[name] = Variable(name)
                arg_params[name] = nd_array(inits[name])
            else:
                raise MXNetError("ONNX import: undefined tensor %r" % name)
        return tensors[name]

    for raw in P.every(graph, 1):  # nodes, topological per spec
        nf = P.parse(raw)
        op = P.as_str(P.first(nf, 4))
        name = P.as_str(P.first(nf, 3)) or op.lower()
        ins = [P.as_str(v) for _, v in nf.get(1, [])]
        outs = [P.as_str(v) for _, v in nf.get(2, [])]
        a = _parse_attrs(nf)

        if op == "Conv":
            k = a.get("kernel_shape", ())
            n = len(k)
            w = inits.get(ins[1])
            res = invoke_symbol("Convolution",
                               [sym_in(x) for x in ins],
                               {"kernel": tuple(k),
                                "stride": _pairs(a.get("strides"), n),
                                "dilate": _pairs(a.get("dilations"), n),
                                "pad": _pairs(a.get("pads"), n, 0),
                                "num_filter": int(w.shape[0]) if w is not None
                                else 0,
                                "num_group": int(a.get("group", 1)),
                                "no_bias": len(ins) == 2}, name=name)
        elif op == "Gemm":
            if a.get("transB", 0) != 1 or a.get("transA", 0) != 0 \
                    or a.get("alpha", 1.0) != 1.0 \
                    or a.get("beta", 1.0) != 1.0:
                raise MXNetError(
                    "ONNX import: Gemm supports transB=1, transA=0, "
                    "alpha=beta=1 only (got %r)" % (a,))
            w = inits.get(ins[1])
            res = invoke_symbol("FullyConnected",
                               [sym_in(x) for x in ins],
                               {"num_hidden": int(w.shape[0]),
                                "no_bias": len(ins) == 2,
                                "flatten": False}, name=name)
        elif op == "BatchNormalization":
            syms = [sym_in(x) for x in ins]
            # running mean/var are AUX states
            for nm in ins[3:5]:
                aux_names.add(nm)
                tensors[nm]._outputs[0][0].is_aux = True
            res = invoke_symbol("BatchNorm", syms,
                               {"eps": float(a.get("epsilon", 1e-5)),
                                "momentum": float(a.get("momentum", 0.9)),
                                "fix_gamma": False,
                                "use_global_stats": True}, name=name)
        elif op in _ACT:
            res = invoke_symbol("Activation", [sym_in(ins[0])],
                               {"act_type": _ACT[op]}, name=name)
        elif op in ("MaxPool", "AveragePool"):
            k = a.get("kernel_shape", ())
            n = len(k)
            attrs = {"kernel": tuple(k),
                     "stride": _pairs(a.get("strides"), n),
                     "pad": _pairs(a.get("pads"), n, 0),
                     "pool_type": "max" if op == "MaxPool" else "avg"}
            if a.get("ceil_mode"):
                attrs["pooling_convention"] = "full"
            if op == "AveragePool":
                # ONNX default EXCLUDES padding from the average
                attrs["count_include_pad"] = \
                    bool(a.get("count_include_pad", 0))
            res = invoke_symbol("Pooling", [sym_in(ins[0])], attrs,
                                name=name)
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            res = invoke_symbol("Pooling", [sym_in(ins[0])],
                               {"global_pool": True, "kernel": (1, 1),
                                "pool_type": "max" if "Max" in op
                                else "avg"}, name=name)
        elif op == "Softmax":
            res = invoke_symbol("softmax", [sym_in(ins[0])],
                               {"axis": int(a.get("axis", -1))}, name=name)
        elif op == "LogSoftmax":
            res = invoke_symbol("log_softmax", [sym_in(ins[0])],
                               {"axis": int(a.get("axis", -1))}, name=name)
        elif op in _ELEMWISE:
            # scalar initializers fold back into *_scalar ops
            if ins[1] in inits and inits[ins[1]].ndim == 0:
                mx_op = {"Add": "_plus_scalar", "Mul": "_mul_scalar",
                         "Sub": "_minus_scalar", "Div": "_div_scalar"}[op]
                consumed.add(ins[1])
                res = invoke_symbol(mx_op, [sym_in(ins[0])],
                                   {"scalar": float(inits[ins[1]])},
                                   name=name)
            else:
                res = invoke_symbol(_ELEMWISE[op],
                                    [sym_in(x) for x in ins], {}, name=name)
        elif op in _UNARY:
            res = invoke_symbol(_UNARY[op], [sym_in(ins[0])], {}, name=name)
        elif op == "Sum":
            res = invoke_symbol("add_n", [sym_in(x) for x in ins], {},
                                name=name)
        elif op == "Concat":
            res = invoke_symbol("Concat", [sym_in(x) for x in ins],
                               {"dim": int(a.get("axis", 1))}, name=name)
        elif op == "Flatten":
            res = invoke_symbol("Flatten", [sym_in(ins[0])], {}, name=name)
        elif op == "Reshape":
            shape = tuple(int(x) for x in inits[ins[1]])
            consumed.add(ins[1])
            res = invoke_symbol("Reshape", [sym_in(ins[0])],
                               {"shape": shape}, name=name)
        elif op == "Transpose":
            res = invoke_symbol("transpose", [sym_in(ins[0])],
                               {"axes": a.get("perm")}, name=name)
        elif op == "Dropout":
            # opset 12: ratio is an optional INPUT; older files use attr
            ratio = 0.5
            if len(ins) > 1 and ins[1] in inits:
                ratio = float(np.ravel(inits[ins[1]])[0])
                consumed.add(ins[1])
            elif "ratio" in a:
                ratio = float(a["ratio"])
            res = invoke_symbol("Dropout", [sym_in(ins[0])],
                               {"p": ratio}, name=name)
        elif op == "LeakyRelu":
            res = invoke_symbol("LeakyReLU", [sym_in(ins[0])],
                               {"act_type": "leaky",
                                "slope": float(a.get("alpha", 0.01))},
                               name=name)
        elif op == "Elu":
            res = invoke_symbol("LeakyReLU", [sym_in(ins[0])],
                               {"act_type": "elu",
                                "slope": float(a.get("alpha", 1.0))},
                               name=name)
        elif op == "Clip":
            # opset 11+: min/max are INPUTS; opset<11 used attributes;
            # spec defaults are +-inf (no clipping on that side)
            lo, hi = -3.4e38, 3.4e38
            if len(ins) > 1 and ins[1] and ins[1] in inits:
                lo = float(np.ravel(inits[ins[1]])[0])
                consumed.add(ins[1])
            elif "min" in a:
                lo = float(a["min"])
            if len(ins) > 2 and ins[2] and ins[2] in inits:
                hi = float(np.ravel(inits[ins[2]])[0])
                consumed.add(ins[2])
            elif "max" in a:
                hi = float(a["max"])
            res = invoke_symbol("clip", [sym_in(ins[0])],
                               {"a_min": lo, "a_max": hi}, name=name)
        elif op == "ConvTranspose":
            k = a.get("kernel_shape", ())
            n = len(k)
            w = inits.get(ins[1])
            res = invoke_symbol("Deconvolution",
                               [sym_in(x) for x in ins],
                               {"kernel": tuple(k),
                                "stride": _pairs(a.get("strides"), n),
                                "dilate": _pairs(a.get("dilations"), n),
                                "pad": _pairs(a.get("pads"), n, 0),
                                "adj": _pairs(a.get("output_padding"),
                                              n, 0),
                                "num_filter": int(w.shape[1]) *
                                int(a.get("group", 1))
                                if w is not None else 0,
                                "num_group": int(a.get("group", 1)),
                                "no_bias": len(ins) == 2}, name=name)
        elif op == "Slice":
            def _ints(slot, key):
                if len(ins) > slot and ins[slot] and ins[slot] in inits:
                    consumed.add(ins[slot])
                    return [int(v) for v in np.ravel(inits[ins[slot]])]
                v = a.get(key)
                return [int(x) for x in v] if v is not None else None
            starts = _ints(1, "starts")
            ends = _ints(2, "ends")
            axes = _ints(3, "axes") or list(range(len(starts)))
            steps = _ints(4, "steps") or [1] * len(starts)
            big = 2 ** 31 - 1
            if all(ax >= 0 for ax in axes):
                nd_hint = max(axes) + 1
                begin = [None] * nd_hint
                end = [None] * nd_hint
                step = [1] * nd_hint
                for ax, st, en, sp in zip(axes, starts, ends, steps):
                    begin[ax] = st
                    end[ax] = None if en >= big else en
                    step[ax] = sp
                res = invoke_symbol("slice", [sym_in(ins[0])],
                                   {"begin": tuple(begin),
                                    "end": tuple(end),
                                    "step": tuple(step)}, name=name)
            else:
                # negative axes (legal per spec): rank unknown until
                # bind, so chain per-axis slice_axis (negative-axis
                # aware); steps would need the rank, so reject them
                if any(sp != 1 for sp in steps):
                    raise MXNetError(
                        "ONNX import: Slice with negative axes AND "
                        "steps != 1 is unsupported")
                res = sym_in(ins[0])
                for j, (ax, st, en) in enumerate(
                        zip(axes, starts, ends)):
                    res = invoke_symbol(
                        "slice_axis", [res],
                        {"axis": ax, "begin": st,
                         "end": None if en >= big else en},
                        name="%s_ax%d" % (name, j))
        elif op == "Unsqueeze":
            axes = a.get("axes")
            if axes is None and len(ins) > 1 and ins[1] in inits:
                consumed.add(ins[1])
                axes = [int(v) for v in np.ravel(inits[ins[1]])]
            res = sym_in(ins[0])
            for ax in sorted(int(x) for x in axes):
                res = invoke_symbol("expand_dims", [res],
                                   {"axis": ax},
                                   name=name + "_ax%d" % ax)
        elif op == "Squeeze":
            axes = a.get("axes")
            if axes is None and len(ins) > 1 and ins[1] in inits:
                consumed.add(ins[1])
                axes = [int(v) for v in np.ravel(inits[ins[1]])]
            res = invoke_symbol(
                "squeeze", [sym_in(ins[0])],
                {"axis": tuple(int(x) for x in axes)
                 if axes is not None else None}, name=name)
        elif op == "Gather":
            res = invoke_symbol("take",
                               [sym_in(ins[0]), sym_in(ins[1])],
                               {"axis": int(a.get("axis", 0))},
                               name=name)
        elif op == "MatMul":
            res = invoke_symbol("_onnx_MatMul",
                               [sym_in(ins[0]), sym_in(ins[1])], {},
                               name=name)
        elif op == "Pad":
            if len(ins) > 1 and ins[1] in inits:
                consumed.add(ins[1])
                pads = [int(v) for v in np.ravel(inits[ins[1]])]
            else:
                pads = [int(x) for x in a.get("pads", ())]
            cval = 0.0
            if len(ins) > 2 and ins[2] and ins[2] in inits:
                consumed.add(ins[2])
                cval = float(np.ravel(inits[ins[2]])[0])
            half = len(pads) // 2
            width = []
            for i in range(half):
                width += [pads[i], pads[half + i]]
            mode = a.get("mode", "constant")
            if isinstance(mode, bytes):
                mode = mode.decode()
            res = invoke_symbol("Pad", [sym_in(ins[0])],
                               {"mode": mode,
                                "pad_width": tuple(width),
                                "constant_value": cval}, name=name)
        elif op in ("Max", "Min", "Pow"):
            mxop = {"Max": "broadcast_maximum",
                    "Min": "broadcast_minimum",
                    "Pow": "broadcast_power"}[op]
            res = invoke_symbol(mxop,
                               [sym_in(ins[0]), sym_in(ins[1])], {},
                               name=name)
        elif op in ("ReduceSum", "ReduceMean", "ReduceMax",
                    "ReduceMin"):
            mxop = {"ReduceSum": "sum", "ReduceMean": "mean",
                    "ReduceMax": "max", "ReduceMin": "min"}[op]
            axes = a.get("axes")
            if axes is None and len(ins) > 1 and ins[1] in inits:
                consumed.add(ins[1])
                axes = [int(v) for v in np.ravel(inits[ins[1]])]
            attrs = {"keepdims": bool(a.get("keepdims", 1))}
            if axes is not None:
                attrs["axis"] = tuple(int(x) for x in axes)
            res = invoke_symbol(mxop, [sym_in(ins[0])], attrs,
                               name=name)
        elif op == "InstanceNormalization":
            res = invoke_symbol("InstanceNorm",
                               [sym_in(x) for x in ins],
                               {"eps": float(a.get("epsilon", 1e-5))},
                               name=name)
        else:
            raise MXNetError(
                "ONNX import: no converter for op %r — extend "
                "mxtpu/contrib/onnx/import_onnx.py" % op)
        for i, out in enumerate(outs):
            tensors[out] = res[i] if len(res) > 1 else res

    out_syms = []
    for raw in P.every(graph, 12):
        fo = P.parse(raw)
        out_syms.append(tensors[P.as_str(P.first(fo, 1))])
    from ...symbol.symbol import Group

    sym = out_syms[0] if len(out_syms) == 1 else Group(out_syms)

    arg_names = set(sym.list_arguments())
    aux_params: Dict[str, Any] = {}
    for name, arr in inits.items():
        if name in consumed:
            continue
        if name in aux_names:
            aux_params[name] = nd_array(arr)
        elif name in arg_names or name in tensors:
            arg_params[name] = nd_array(arr)
    for nm in aux_names:
        arg_params.pop(nm, None)
    return sym, arg_params, aux_params
