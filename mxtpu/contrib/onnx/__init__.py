"""ONNX interchange (reference `python/mxnet/contrib/onnx/`):
`export_model` writes traced Symbols + params as real `.onnx`
protobufs; `import_model` loads them back as (Symbol, arg_params,
aux_params).  Self-contained — the protobuf wire format is encoded
directly (`_proto.py`), no `onnx` package needed."""
from .export_onnx import export_model, export_symbol  # noqa: F401
from .import_onnx import import_model  # noqa: F401

# reference exposes these under mx.contrib.onnx.mx2onnx/onnx2mx too
get_model_metadata = None  # pragma: no cover (reference parity stub)
