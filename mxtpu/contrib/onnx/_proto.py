"""Minimal protobuf wire codec for ONNX ModelProto.

The environment has no `onnx` package (zero egress), so this module
encodes/decodes the protobuf wire format directly for the subset of
fields export/import use.  Files written here are REAL `.onnx`
protobufs — loadable by onnxruntime/netron elsewhere — not a private
serialization.  Field numbers follow onnx/onnx.proto (IR v7/opset 12).
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


# ---------------------------------------------------------------------------
# primitive writers
# ---------------------------------------------------------------------------

def _varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def w_varint(field: int, v: int) -> bytes:
    return _tag(field, _VARINT) + _varint(int(v))


def w_bytes(field: int, payload: bytes) -> bytes:
    return _tag(field, _LEN) + _varint(len(payload)) + payload


def w_str(field: int, s: str) -> bytes:
    return w_bytes(field, s.encode("utf-8"))


def w_float(field: int, f: float) -> bytes:
    return _tag(field, _I32) + struct.pack("<f", f)


def w_packed_floats(field: int, fs) -> bytes:
    return w_bytes(field, b"".join(struct.pack("<f", float(f)) for f in fs))


def w_packed_ints(field: int, vs) -> bytes:
    return w_bytes(field, b"".join(_varint(int(v)) for v in vs))


# ---------------------------------------------------------------------------
# generic reader
# ---------------------------------------------------------------------------

def parse(buf: bytes) -> Dict[int, List[Tuple[int, Any]]]:
    """Parse one message into {field: [(wire_type, raw_value), ...]}.
    LEN fields return raw bytes (parse nested messages recursively)."""
    out: Dict[int, List[Tuple[int, Any]]] = {}
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == _VARINT:
            v, i = _read_varint(buf, i)
        elif wire == _I64:
            v = struct.unpack_from("<q", buf, i)[0]
            i += 8
        elif wire == _LEN:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == _I32:
            v = struct.unpack_from("<f", buf, i)[0]
            i += 4
        else:
            raise ValueError("unsupported wire type %d" % wire)
        out.setdefault(field, []).append((wire, v))
    return out


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = v = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            if v >= 1 << 63:
                v -= 1 << 64
            return v, i
        shift += 7


def first(fields, num, default=None):
    vals = fields.get(num)
    return vals[0][1] if vals else default


def every(fields, num):
    return [v for _, v in fields.get(num, [])]


def as_str(v, default=""):
    return v.decode("utf-8") if isinstance(v, (bytes, bytearray)) else \
        (v if v is not None else default)


def unpack_ints(raw) -> List[int]:
    """Packed repeated varint field -> list."""
    if raw is None:
        return []
    if isinstance(raw, int):
        return [raw]
    out, i = [], 0
    while i < len(raw):
        v, i = _read_varint(raw, i)
        out.append(v)
    return out
