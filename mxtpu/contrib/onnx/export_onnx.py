"""Symbol -> ONNX export (reference `contrib/onnx/mx2onnx/export_model.py`).

Maps the traced Symbol IR onto ONNX opset-12 nodes and writes a real
ModelProto protobuf via `_proto.py`.  Covered surface = what the gluon
model zoo traces to (Conv/BN/activations/pooling/FC/residual adds/
concat/flatten/softmax/dropout/reshape + scalar arithmetic); anything
else raises with the op name so gaps are loud, like the reference's
per-op converter registry.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...base import MXNetError
from . import _proto as P

_DT = {np.dtype(np.float32): 1, np.dtype(np.uint8): 2,
       np.dtype(np.int8): 3, np.dtype(np.int32): 6,
       np.dtype(np.int64): 7, np.dtype(np.bool_): 9,
       np.dtype(np.float64): 11}

_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign"}
_ELEMWISE = {"elemwise_add": "Add", "broadcast_add": "Add",
             "elemwise_mul": "Mul", "broadcast_mul": "Mul",
             "elemwise_sub": "Sub", "broadcast_sub": "Sub",
             "elemwise_div": "Div", "broadcast_div": "Div",
             "_grad_add": "Add"}
_UNARY = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
          "negative": "Neg", "floor": "Floor", "ceil": "Ceil",
          "_copy": "Identity", "BlockGrad": "Identity",
          "make_loss": "Identity", "MakeLoss": "Identity"}


def _attr_f(name: str, v: float) -> bytes:
    return P.w_str(1, name) + P.w_float(2, float(v)) + P.w_varint(20, 1)


def _attr_i(name: str, v: int) -> bytes:
    return P.w_str(1, name) + P.w_varint(3, int(v)) + P.w_varint(20, 2)


def _attr_s(name: str, s: str) -> bytes:
    return P.w_str(1, name) + P.w_bytes(4, s.encode()) + P.w_varint(20, 3)


def _attr_ints(name: str, vs) -> bytes:
    body = P.w_str(1, name) + P.w_varint(20, 7)
    for v in vs:
        body += P.w_varint(8, int(v))
    return body


def _tensor(name: str, arr: np.ndarray) -> bytes:
    arr = np.ascontiguousarray(arr)
    if arr.dtype not in _DT:
        raise MXNetError("unsupported ONNX dtype %s" % arr.dtype)
    body = b"".join(P.w_varint(1, d) for d in arr.shape)
    body += P.w_varint(2, _DT[arr.dtype])
    body += P.w_str(8, name)
    body += P.w_bytes(9, arr.tobytes())
    return body


def _value_info(name: str, shape, elem_type: int = 1) -> bytes:
    dims = b"".join(P.w_bytes(1, P.w_varint(1, d)) for d in shape)
    tensor_t = P.w_varint(1, elem_type) + P.w_bytes(2, dims)
    return P.w_str(1, name) + P.w_bytes(2, P.w_bytes(1, tensor_t))


def _node(op_type: str, inputs: List[str], outputs: List[str],
          name: str, attrs: List[bytes] = ()) -> bytes:
    body = b"".join(P.w_str(1, i) for i in inputs)
    body += b"".join(P.w_str(2, o) for o in outputs)
    body += P.w_str(3, name) + P.w_str(4, op_type)
    body += b"".join(P.w_bytes(5, a) for a in attrs)
    return body


def _pair(v, n=2, default=1):
    t = tuple(int(x) for x in v) if v else (default,) * n
    return t if len(t) == n else t * n


class _Exporter(object):
    def __init__(self, sym, params: Dict[str, np.ndarray],
                 aux: Dict[str, np.ndarray], shapes=None):
        self.shapes = shapes or {}
        self.sym = sym
        self.params = dict(params)
        self.aux = dict(aux)
        self.nodes: List[bytes] = []
        self.extra_inits: Dict[str, np.ndarray] = {}
        self.used_params: set = set()
        self._uid = 0

    def uid(self, base):
        self._uid += 1
        return "%s_%d" % (base, self._uid)

    def entry_shape(self, entry):
        node, idx = entry
        if node.is_variable:
            return self.shapes.get(node.name)
        return self.shapes.get(("out", id(node), idx))

    def tname(self, entry) -> str:
        node, idx = entry
        if node.is_variable:
            return node.name
        if node.num_outputs() == 1:
            return node.name + "_output"
        return "%s_output%d" % (node.name, idx)

    def const(self, base, arr) -> str:
        name = self.uid(base)
        self.extra_inits[name] = np.asarray(arr)
        return name

    def emit(self, op_type, ins, outs, name, attrs=()):
        self.nodes.append(_node(op_type, ins, outs, name, list(attrs)))

    # -- per-op conversion ------------------------------------------------
    def convert(self, node):
        a = node.attrs
        ins = [self.tname(e) for e in node.inputs]
        out = self.tname((node, 0))
        op = node.op.name
        for p in ins:
            if p in self.params or p in self.aux:
                self.used_params.add(p)
        if op in ("Convolution", "Convolution_v1"):
            k = tuple(int(x) for x in a["kernel"])
            n = len(k)
            attrs = [_attr_ints("kernel_shape", k),
                     _attr_ints("strides", _pair(a.get("stride"), n)),
                     _attr_ints("dilations", _pair(a.get("dilate"), n)),
                     _attr_ints("pads", _pair(a.get("pad"), n, 0) * 2),
                     _attr_i("group", a.get("num_group", 1))]
            self.emit("Conv", ins[:2 if a.get("no_bias") else 3],
                      [out], node.name, attrs)
        elif op == "FullyConnected":
            x = ins[0]
            if a.get("flatten", True):
                flat = self.uid(node.name + "_flat")
                self.emit("Flatten", [x], [flat], flat, [_attr_i("axis", 1)])
                x = flat
            gemm_in = [x, ins[1]] + ([] if a.get("no_bias") else [ins[2]])
            self.emit("Gemm", gemm_in, [out], node.name,
                      [_attr_i("transB", 1)])
        elif op in ("BatchNorm", "BatchNorm_v1", "_contrib_SyncBatchNorm"):
            gamma = ins[1]
            if a.get("fix_gamma", True):
                shape = (self.params.get(ins[1]) if ins[1] in self.params
                         else np.ones(1)).shape
                gamma = self.const(node.name + "_fixed_gamma",
                                   np.ones(shape, np.float32))
            self.emit("BatchNormalization",
                      [ins[0], gamma, ins[2], ins[3], ins[4]], [out],
                      node.name,
                      [_attr_f("epsilon", a.get("eps", 1e-3)),
                       _attr_f("momentum", a.get("momentum", 0.9))])
        elif op == "Activation":
            act = a.get("act_type", "relu")
            if act not in _ACT:
                raise MXNetError("ONNX export: act_type %r" % act)
            self.emit(_ACT[act], ins, [out], node.name)
        elif op == "Pooling":
            ptype = a.get("pool_type", "max")
            if a.get("global_pool", False):
                self.emit("GlobalMaxPool" if ptype == "max"
                          else "GlobalAveragePool", ins, [out], node.name)
            else:
                k = tuple(int(x) for x in a["kernel"])
                n = len(k)
                attrs = [_attr_ints("kernel_shape", k),
                         _attr_ints("strides", _pair(a.get("stride"), n)),
                         _attr_ints("pads", _pair(a.get("pad"), n, 0) * 2)]
                if a.get("pooling_convention", "valid") == "full":
                    attrs.append(_attr_i("ceil_mode", 1))
                if ptype == "avg":
                    attrs.append(_attr_i(
                        "count_include_pad",
                        1 if a.get("count_include_pad", True) else 0))
                self.emit("MaxPool" if ptype == "max" else "AveragePool",
                          ins, [out], node.name, attrs)
        elif op in ("softmax", "SoftmaxActivation"):
            self.emit("Softmax", ins[:1], [out], node.name,
                      [_attr_i("axis", a.get("axis", -1))])
        elif op in ("SoftmaxOutput", "Softmax"):
            self.emit("Softmax", ins[:1], [out], node.name,
                      [_attr_i("axis", -1)])
        elif op == "log_softmax":
            self.emit("LogSoftmax", ins[:1], [out], node.name,
                      [_attr_i("axis", a.get("axis", -1))])
        elif op in _ELEMWISE:
            self.emit(_ELEMWISE[op], ins, [out], node.name)
        elif op in _UNARY:
            self.emit(_UNARY[op], ins, [out], node.name)
        elif op == "add_n":
            self.emit("Sum", ins, [out], node.name)
        elif op == "Concat":
            self.emit("Concat", ins, [out], node.name,
                      [_attr_i("axis", a.get("dim", 1))])
        elif op == "Flatten":
            self.emit("Flatten", ins, [out], node.name, [_attr_i("axis", 1)])
        elif op == "Reshape":
            shape = self.const(node.name + "_shape",
                               np.asarray(a.get("shape", ()), np.int64))
            self.emit("Reshape", [ins[0], shape], [out], node.name)
        elif op == "transpose":
            axes = a.get("axes")
            self.emit("Transpose", ins, [out], node.name,
                      [_attr_ints("perm", axes)] if axes else [])
        elif op == "Dropout":
            # opset 12: ratio travels as an optional input tensor
            ratio = self.const(node.name + "_ratio",
                               np.asarray(a.get("p", 0.5), np.float32))
            self.emit("Dropout", [ins[0], ratio], [out], node.name)
        elif op == "LeakyReLU":
            act = a.get("act_type", "leaky")
            if act == "leaky":
                self.emit("LeakyRelu", ins[:1], [out], node.name,
                          [_attr_f("alpha", a.get("slope", 0.25))])
            elif act == "elu":
                self.emit("Elu", ins[:1], [out], node.name,
                          [_attr_f("alpha", a.get("slope", 1.0))])
            else:
                raise MXNetError("ONNX export: LeakyReLU %r" % act)
        elif op == "clip":
            # opset 11+: min/max are INPUT tensors, not attributes
            lo = self.const(node.name + "_min",
                            np.asarray(a.get("a_min", 0.0), np.float32))
            hi = self.const(node.name + "_max",
                            np.asarray(a.get("a_max", 0.0), np.float32))
            self.emit("Clip", [ins[0], lo, hi], [out], node.name)
        elif op in ("_mul_scalar", "_plus_scalar", "_minus_scalar",
                    "_div_scalar"):
            onnx_op = {"_mul_scalar": "Mul", "_plus_scalar": "Add",
                       "_minus_scalar": "Sub", "_div_scalar": "Div"}[op]
            s = self.const(node.name + "_scalar",
                           np.asarray(a.get("scalar", 0.0), np.float32))
            self.emit(onnx_op, [ins[0], s], [out], node.name)
        elif op == "mean" and a.get("axis") in ((2, 3), [2, 3]) \
                and not a.get("keepdims"):
            gap = self.uid(node.name + "_gap")
            self.emit("GlobalAveragePool", ins, [gap], gap)
            self.emit("Flatten", [gap], [out], node.name,
                      [_attr_i("axis", 1)])
        elif op == "Deconvolution":
            k = tuple(int(x) for x in a["kernel"])
            n = len(k)
            attrs = [_attr_ints("kernel_shape", k),
                     _attr_ints("strides", _pair(a.get("stride"), n)),
                     _attr_ints("dilations", _pair(a.get("dilate"), n)),
                     _attr_ints("pads", _pair(a.get("pad"), n, 0) * 2),
                     _attr_i("group", a.get("num_group", 1))]
            if a.get("adj"):
                attrs.append(_attr_ints("output_padding",
                                        _pair(a.get("adj"), n, 0)))
            self.emit("ConvTranspose",
                      ins[:2 if a.get("no_bias") else 3], [out],
                      node.name, attrs)
        elif op == "slice_axis":
            ax = int(a["axis"])
            end = a.get("end")
            ends = self.const(node.name + "_ends", np.asarray(
                [2 ** 31 - 1 if end in (None, "None") else int(end)],
                np.int64))
            starts = self.const(node.name + "_starts",
                                np.asarray([int(a.get("begin", 0))],
                                           np.int64))
            axes = self.const(node.name + "_axes",
                              np.asarray([ax], np.int64))
            self.emit("Slice", [ins[0], starts, ends, axes], [out],
                      node.name)
        elif op == "slice":
            begin = [0 if b in (None, "None") else int(b)
                     for b in a.get("begin", ())]
            end = [2 ** 31 - 1 if e in (None, "None") else int(e)
                   for e in a.get("end", ())]
            step = [1 if st in (None, "None") else int(st)
                    for st in (a.get("step") or (1,) * len(begin))]
            starts = self.const(node.name + "_starts",
                                np.asarray(begin, np.int64))
            ends = self.const(node.name + "_ends",
                              np.asarray(end, np.int64))
            axes = self.const(node.name + "_axes",
                              np.arange(len(begin), dtype=np.int64))
            steps = self.const(node.name + "_steps",
                               np.asarray(step, np.int64))
            self.emit("Slice", [ins[0], starts, ends, axes, steps],
                      [out], node.name)
        elif op == "expand_dims":
            # opset 12: axes is an ATTRIBUTE of Unsqueeze
            self.emit("Unsqueeze", ins, [out], node.name,
                      [_attr_ints("axes", (int(a["axis"]),))])
        elif op == "squeeze":
            ax = a.get("axis")
            if ax is None:
                self.emit("Squeeze", ins, [out], node.name)
            else:
                axes = (ax,) if isinstance(ax, int) else tuple(ax)
                self.emit("Squeeze", ins, [out], node.name,
                          [_attr_ints("axes", axes)])
        elif op in ("Embedding", "take"):
            # Gather(data, indices): mxnet argument order is reversed
            data, idx = (ins[1], ins[0]) if op == "Embedding" \
                else (ins[0], ins[1])
            self.emit("Gather", [data, idx], [out], node.name,
                      [_attr_i("axis", int(a.get("axis", 0)))])
        elif op == "dot":
            if a.get("transpose_a") or a.get("transpose_b"):
                raise MXNetError("ONNX export: transposed dot")
            for e in node.inputs:
                shp = self.entry_shape(e)
                if shp is not None and len(shp) > 2:
                    # mxnet dot on >2-D contracts last-with-first —
                    # NOT MatMul's batched semantics
                    raise MXNetError(
                        "ONNX export: dot with ndim>2 operand has no "
                        "MatMul equivalent (use batch_dot)")
            self.emit("MatMul", ins, [out], node.name)
        elif op == "batch_dot":
            if a.get("transpose_a") or a.get("transpose_b"):
                raise MXNetError("ONNX export: transposed batch_dot")
            self.emit("MatMul", ins, [out], node.name)
        elif op in ("Pad", "pad"):
            width = tuple(int(x) for x in a["pad_width"])
            half = len(width) // 2
            onnx_pads = [width[2 * i] for i in range(half)] + \
                [width[2 * i + 1] for i in range(half)]
            pads = self.const(node.name + "_pads",
                              np.asarray(onnx_pads, np.int64))
            cval = self.const(node.name + "_cval",
                              np.asarray(a.get("constant_value", 0.0),
                                         np.float32))
            mode = a.get("mode", "constant")
            self.emit("Pad", [ins[0], pads, cval], [out], node.name,
                      [_attr_s("mode", {"constant": "constant",
                                        "edge": "edge",
                                        "reflect": "reflect"}[mode])])
        elif op in ("broadcast_maximum", "_maximum"):
            self.emit("Max", ins, [out], node.name)
        elif op in ("broadcast_minimum", "_minimum"):
            self.emit("Min", ins, [out], node.name)
        elif op in ("broadcast_power", "_power"):
            self.emit("Pow", ins, [out], node.name)
        elif op in ("sum", "mean", "max", "min") :
            onnx_op = {"sum": "ReduceSum", "mean": "ReduceMean",
                       "max": "ReduceMax", "min": "ReduceMin"}[op]
            attrs = [_attr_i("keepdims",
                             1 if a.get("keepdims") else 0)]
            ax = a.get("axis")
            if ax is not None and ax != "None":
                axes = (ax,) if isinstance(ax, int) else tuple(ax)
                attrs.append(_attr_ints("axes", axes))
            self.emit(onnx_op, ins, [out], node.name, attrs)
        elif op == "InstanceNorm":
            self.emit("InstanceNormalization", ins, [out], node.name,
                      [_attr_f("epsilon", a.get("eps", 1e-3))])
        else:
            raise MXNetError(
                "ONNX export: no converter for op %r (node %r) — "
                "extend mxtpu/contrib/onnx/export_onnx.py" % (op, node.name))


def export_symbol(sym, params: Dict[str, Any], aux: Dict[str, Any],
                  input_shapes: Dict[str, Tuple[int, ...]],
                  model_name: str = "mxtpu") -> bytes:
    """Serialize (sym, params) to ONNX ModelProto bytes."""
    pnp = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
           for k, v in (params or {}).items()}
    anp = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
           for k, v in (aux or {}).items()}
    known = dict(input_shapes)
    for _pname, _parr in {**pnp, **anp}.items():
        known.setdefault(_pname, tuple(_parr.shape))
    from ...symbol.symbol import _infer_graph

    try:
        shape_map, _ = _infer_graph(sym, known, {}, partial=True)
    except Exception:
        shape_map = {}
    ex = _Exporter(sym, pnp, anp, shape_map)
    label_like = set()
    for node in sym._topo():
        if node.is_variable:
            continue
        if node.op.name in ("SoftmaxOutput", "Softmax",
                            "LinearRegressionOutput",
                            "LogisticRegressionOutput",
                            "MAERegressionOutput", "SVMOutput"):
            for src, _ in node.inputs[1:]:
                if src.is_variable:
                    label_like.add(src.name)
        ex.convert(node)

    inits = b""
    for name in sorted(ex.used_params):
        arr = pnp.get(name, anp.get(name))
        inits += P.w_bytes(5, _tensor(name, arr))
    for name, arr in ex.extra_inits.items():
        inits += P.w_bytes(5, _tensor(name, arr))

    inputs = b""
    all_params = set(pnp) | set(anp) | set(ex.extra_inits)
    for node in sym._topo():
        if node.is_variable and node.name not in all_params \
                and node.name not in label_like:
            if node.name not in input_shapes:
                raise MXNetError("input_shapes missing %r" % node.name)
            inputs += P.w_bytes(11, _value_info(node.name,
                                                input_shapes[node.name]))
    outputs = b""
    # seed inference with the param shapes too — attrs alone cannot
    # determine weight shapes for ops like dot/MatMul
    known = dict(input_shapes)
    arg_names = set(sym.list_arguments())
    for name, arr in {**pnp, **anp}.items():
        if name in arg_names and name not in known:
            known[name] = tuple(arr.shape)
    _, out_shapes, _ = sym.infer_shape(**known)
    for name, shape in zip(sym.list_outputs(), out_shapes):
        outputs += P.w_bytes(12, _value_info(name, shape))

    graph = b"".join(P.w_bytes(1, n) for n in ex.nodes)
    graph += P.w_str(2, model_name) + inits + inputs + outputs
    opset = P.w_str(1, "") + P.w_varint(2, 12)
    model = (P.w_varint(1, 7) + P.w_str(2, "mxtpu") +
             P.w_str(3, "0.1") + P.w_bytes(7, graph) + P.w_bytes(8, opset))
    return model


def export_model(sym, params, aux, input_shapes, onnx_file_path,
                 model_name: str = "mxtpu") -> str:
    """Write the model to `onnx_file_path` and return the path
    (reference `onnx_mxnet.export_model`)."""
    if hasattr(sym, "_cached_symbol"):  # allow HybridBlock-ish inputs
        sym = sym._cached_symbol
    blob = export_symbol(sym, params, aux, input_shapes, model_name)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    return onnx_file_path
