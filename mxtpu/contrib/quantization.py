"""INT8 post-training quantization: calibration + graph rewrite.

TPU-native counterpart of the reference quantization workflow
(`python/mxnet/contrib/quantization.py:423` quantize_model;
`src/operator/quantization/quantize_graph_pass.cc`).  The reference
rewrites the NNVM graph in C++; here the rewrite is a pure-Python pass
over the Symbol IR that

  1. runs CALIBRATION batches through the fp32 graph and records each
     quantized op input's dynamic range — `naive` (global min/max) or
     `entropy` (KL-optimal threshold over a histogram, reference
     `_get_optimal_threshold`);
  2. rebuilds the graph with `_contrib_quantize_v2` →
     `_contrib_quantized_{conv,fully_connected}` → `_contrib_dequantize`
     islands around every supported op (per-op dequant keeps the pass
     simple and numerically transparent; XLA fuses the casts);
  3. quantizes the touched parameters OFFLINE to int8 NDArrays with
     their own recorded ranges (weights symmetric over max-abs).

The int8 compute ops accumulate in int32 on the MXU
(`mxtpu/ops/quantization.py`), so the quantized graph still rides the
systolic array.
"""
from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, array as nd_array
from ..subgraph import SubgraphProperty as _SubgraphProperty
from ..symbol.register import invoke_symbol
from ..symbol.symbol import Symbol, Variable

__all__ = ["quantize_model", "quantize_symbol", "quantize_params",
           "calibrate_ranges"]

# ops with an int8 kernel (reference quantize_graph_pass.cc
# quantized-op registry); value = quantized op name
_QUANTIZABLE = {
    "Convolution": "_contrib_quantized_conv",
    "FullyConnected": "_contrib_quantized_fully_connected",
}


def _max_abs(arr: np.ndarray) -> float:
    """Symmetric range of a tensor; never 0 (an all-zero param — e.g. a
    freshly-initialized bias — must quantize to zeros, not NaN)."""
    t = float(np.max(np.abs(arr))) if arr.size else 1.0
    return t if t > 0 else 1.0


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------

def _optimal_threshold(hist: np.ndarray, edges: np.ndarray,
                       num_quantized_bins: int = 255,
                       max_clip_frac: float = 0.01) -> float:
    """KL-divergence-optimal |threshold| over a symmetric histogram
    (reference `quantization.py _get_optimal_threshold` / TensorRT's
    entropy calibration).  Scans candidate clip points and keeps the one
    whose clipped+quantized distribution diverges least from the
    original.

    `max_clip_frac` bounds the calibration mass a candidate may clip:
    the raw KL objective hides clipped mass in the edge bins, so on
    concentrated distributions (ReLU stacks, untrained nets) it would
    happily clip half the data — the bound keeps the search inside the
    99th-percentile window, which is also where TensorRT-style
    calibration lands on well-behaved data."""
    n_bins = len(hist)
    assert n_bins % 2 == 1  # symmetric around zero
    zero = n_bins // 2
    best_kl, best_t = np.inf, float(edges[-1])
    total = hist.sum()
    if total == 0:
        return best_t
    p_full = hist.astype(np.float64)
    for width in range(num_quantized_bins // 2, zero + 1):
        lo, hi = zero - width, zero + width + 1
        clipped = p_full[:lo].sum() + p_full[hi:].sum()
        if clipped > max_clip_frac * total:
            continue
        p = p_full[lo:hi].copy()
        # outliers collapse into the edge bins (clipping)
        p[0] += p_full[:lo].sum()
        p[-1] += p_full[hi:].sum()
        nonzero = p > 0
        if nonzero.sum() < 2:
            continue
        # quantize p into num_quantized_bins, then expand back
        # (vectorized: per-bin sums/counts via add.reduceat)
        factor = len(p) / num_quantized_bins
        starts = np.floor(np.arange(num_quantized_bins) * factor) \
            .astype(np.int64)
        bin_of = np.minimum((np.arange(len(p)) / factor).astype(np.int64),
                            num_quantized_bins - 1)
        sums = np.add.reduceat(p, starts)
        counts = np.add.reduceat(nonzero.astype(np.float64), starts)
        avg = np.where(counts > 0, sums / np.maximum(counts, 1), 0.0)
        q = np.where(nonzero, avg[bin_of], 0.0)
        p_n = p / p.sum()
        q_n = q / q.sum() if q.sum() > 0 else q
        mask = (p_n > 0) & (q_n > 0)
        if not mask.any():
            continue
        kl = float(np.sum(p_n[mask] * np.log(p_n[mask] / q_n[mask])))
        if kl < best_kl:
            best_kl = kl
            best_t = float(max(abs(edges[lo]), abs(edges[hi])))
    return best_t


class _RangeCollector(object):
    """Accumulates per-tensor ranges over calibration batches."""

    def __init__(self, mode: str, num_bins: int = 8001):
        self.mode = mode
        self.num_bins = num_bins
        self.minmax: Dict[str, Tuple[float, float]] = {}
        self.hists: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}

    def update(self, name: str, arr: np.ndarray):
        lo, hi = float(arr.min()), float(arr.max())
        if name in self.minmax:
            plo, phi = self.minmax[name]
            self.minmax[name] = (min(lo, plo), max(hi, phi))
        else:
            self.minmax[name] = (lo, hi)
        if self.mode == "entropy":
            t = max(abs(lo), abs(hi), 1e-8)
            if name in self.hists:
                hist, edges = self.hists[name]
                if t > edges[-1]:  # re-bin into the wider range
                    new_edges = np.linspace(-t, t, self.num_bins + 1)
                    centers = (edges[:-1] + edges[1:]) / 2
                    new_hist, _ = np.histogram(centers, bins=new_edges,
                                               weights=hist)
                    hist, edges = new_hist, new_edges
                add, _ = np.histogram(arr, bins=edges)
                self.hists[name] = (hist + add, edges)
            else:
                edges = np.linspace(-t, t, self.num_bins + 1)
                hist, _ = np.histogram(arr, bins=edges)
                self.hists[name] = (hist, edges)

    def ranges(self) -> Dict[str, Tuple[float, float]]:
        if self.mode != "entropy":
            return dict(self.minmax)
        out = {}
        for name, (hist, edges) in self.hists.items():
            t = _optimal_threshold(hist, edges)
            out[name] = (-t, t)
        return out


def calibrate_ranges(sym: Symbol, arg_params, aux_params, calib_data,
                     data_names=("data",), label_names=("softmax_label",),
                     num_calib_examples: Optional[int] = None,
                     calib_mode: str = "naive",
                     excluded_sym_names=()) -> Dict[str, Tuple[float, float]]:
    """Run calibration batches through the fp32 graph and return
    {internal-output-name: (min, max)} for every tensor feeding a
    quantized op (reference `_collect_layer_statistics`)."""
    need: List[str] = []
    for node in sym._topo():
        if node.is_variable or node.name in excluded_sym_names:
            continue
        if node.op.name in _QUANTIZABLE:
            src, idx = node.inputs[0]
            if not src.is_variable:
                nm = src.name + "_output" \
                    if src.num_outputs() == 1 \
                    else "%s_output%d" % (src.name, idx)
            else:
                nm = src.name
            need.append(nm)
    internals = sym.get_internals()
    outs = [internals[nm] for nm in dict.fromkeys(need)
            if nm not in sym.list_arguments()]
    collector = _RangeCollector(calib_mode)

    if outs:
        from ..symbol.symbol import Group

        group = Group(outs)
        from ..context import cpu

        shapes = {}
        batch0 = None
        calib_data.reset()
        for batch in calib_data:
            batch0 = batch
            break
        if batch0 is None:
            raise MXNetError("empty calibration data")
        for n, d in zip(data_names, batch0.data):
            shapes[n] = d.shape
        exe = group.simple_bind(ctx=cpu(), grad_req="null", **shapes)
        for k, v in (arg_params or {}).items():
            if k in exe.arg_dict:
                v.copyto(exe.arg_dict[k])
        for k, v in (aux_params or {}).items():
            if k in exe.aux_dict:
                v.copyto(exe.aux_dict[k])

        seen = 0
        calib_data.reset()
        names = group.list_outputs()
        for batch in calib_data:
            feed = {n: d for n, d in zip(data_names, batch.data)}
            outs_nd = exe.forward(is_train=False, **feed)
            for nm, o in zip(names, outs_nd):
                collector.update(nm, o.asnumpy())
            # the graph INPUT also needs a range
            for n, d in zip(data_names, batch.data):
                collector.update(n, d.asnumpy())
            seen += batch.data[0].shape[0]
            if num_calib_examples is not None and seen >= num_calib_examples:
                break
    else:
        calib_data.reset()
        seen = 0
        for batch in calib_data:
            for n, d in zip(data_names, batch.data):
                collector.update(n, d.asnumpy())
            seen += batch.data[0].shape[0]
            if num_calib_examples is not None and seen >= num_calib_examples:
                break
    logging.getLogger(__name__).info(
        "calibrated %d tensors over %d examples (%s mode)",
        len(collector.minmax), seen, calib_mode)
    return collector.ranges()


# ---------------------------------------------------------------------------
# Graph rewrite
# ---------------------------------------------------------------------------

class _QuantizeSelector:
    """Single-node regions over quantizable ops (the INT8 rewrite is a
    per-op island; no growth)."""

    def __init__(self, prop):
        self._prop = prop

    def select(self, node):
        return self._prop._quantizable(node)

    def select_input(self, node, input_node):
        return False

    def select_output(self, node, output_node):
        return False

    def filter(self, candidates):
        return candidates


class QuantizeProperty(_SubgraphProperty):
    """INT8 rewrite as a subgraph backend (`mxtpu.subgraph`): each
    quantizable node is a one-node region replaced by a
    quantize → int8-op → dequantize island.  The reference implements
    the same rewrite as the MKLDNN_QUANTIZE subgraph property
    (`src/operator/subgraph/mkldnn/mkldnn_subgraph_property.cc`) over
    `quantize_graph_pass.cc`."""

    needs_params = False  # params are quantized separately (offline)

    def __init__(self, ranges, excluded_sym_names=()):
        self.ranges = ranges
        self.excluded = set(excluded_sym_names)
        self.offline: List[str] = []

    def _in_name(self, node):
        from ..subgraph import _entry_name

        return _entry_name(*node.inputs[0])

    def _quantizable(self, node):
        if node.is_variable or node.op.name not in _QUANTIZABLE:
            return False
        if node.name in self.excluded:
            return False
        if len(node.inputs) < 2 or not node.inputs[1][0].is_variable:
            return False
        return self.ranges is None or self._in_name(node) in self.ranges

    def create_selector(self):
        return _QuantizeSelector(self)

    def filter_region(self, region, consumers, head_ids):
        return region

    def create_subgraph_node(self, sub_sym, region, input_names, sid):
        node = region[0]
        qop = _QUANTIZABLE[node.op.name]
        qattrs = {}
        if self.ranges is not None:
            lo, hi = self.ranges[self._in_name(node)]
            qattrs = {"min_calib_range": float(lo),
                      "max_calib_range": float(hi)}
        data_ph = Variable(input_names[0])
        q = invoke_symbol("_contrib_quantize_v2", [data_ph], qattrs,
                          name=node.name + "_quantize")
        wname = node.inputs[1][0].name
        self.offline.append(wname)
        qw = Variable(wname + "_quantize")
        wmin, wmax = Variable(wname + "_min"), Variable(wname + "_max")
        no_bias = node.attrs.get("no_bias", False)
        if not no_bias and len(node.inputs) >= 3 \
                and node.inputs[2][0].is_variable:
            bname = node.inputs[2][0].name
            self.offline.append(bname)
            qb = Variable(bname + "_quantize")
            bmin, bmax = Variable(bname + "_min"), Variable(bname + "_max")
        else:
            qb = Variable(node.name + "_no_bias")  # zero int8 stand-in
            bmin, bmax = wmin, wmax  # same vars, no duplicates
        q_out = q  # quantize_v2 has 3 visible outputs (data, min, max)
        core = invoke_symbol(
            qop, [q_out[0], qw, qb, q_out[1], q_out[2],
                  wmin, wmax, bmin, bmax],
            dict(node.attrs), name=node.name + "_quantized")
        deq = invoke_symbol(
            "_contrib_dequantize", [core[0], core[1], core[2]], {},
            name=node.name + "_dequantize")
        return deq

    def transform_params(self, applied, arg_params, aux_params):
        return arg_params, aux_params


def quantize_symbol(sym: Symbol,
                    ranges: Optional[Dict[str, Tuple[float, float]]],
                    excluded_sym_names=(),
                    quantized_dtype: str = "int8") -> Tuple[Symbol, List[str]]:
    """Rebuild `sym` with int8 islands around every quantizable op whose
    input range was calibrated; ``ranges=None`` quantizes EVERY
    supported op with runtime (dynamic) min/max — the calib_mode='none'
    workflow.  Returns (qsym, names of params that `quantize_params`
    must convert offline).

    The rewrite itself runs through the pluggable subgraph framework
    (`mxtpu.subgraph.partition_with_property` with `QuantizeProperty`)."""
    if quantized_dtype != "int8":
        raise MXNetError("only int8 is supported (got %r)" % quantized_dtype)
    from ..subgraph import partition_with_property

    prop = QuantizeProperty(ranges, excluded_sym_names)
    qsym = partition_with_property(sym, prop)
    return qsym, prop.offline


def quantize_params(qsym: Symbol, arg_params: Dict[str, NDArray],
                    offline: List[str]) -> Dict[str, NDArray]:
    """Offline-quantize `offline` params to int8 with symmetric max-abs
    ranges; other params pass through (reference quantize_params)."""
    out: Dict[str, NDArray] = {}
    qargs = set(qsym.list_arguments())
    for name, arr in arg_params.items():
        if name in offline:
            host = arr.asnumpy()
            t = _max_abs(host)
            qv = np.clip(np.round(host / t * 127.0), -127, 127) \
                .astype(np.int8)
            if name + "_quantize" in qargs:
                out[name + "_quantize"] = nd_array(qv)
                out[name + "_min"] = nd_array(
                    np.asarray([-t], np.float32))
                out[name + "_max"] = nd_array(
                    np.asarray([t], np.float32))
        if name in qargs:
            out[name] = arr
    # zero int8 stand-ins for no-bias slots
    for name in qargs:
        if name.endswith("_no_bias") and name not in out:
            out[name] = nd_array(np.zeros((1,), np.int8))
    return out


def quantize_model(sym: Symbol, arg_params, aux_params,
                   data_names=("data",), label_names=("softmax_label",),
                   ctx=None, excluded_sym_names=(),
                   calib_mode: str = "naive", calib_data=None,
                   num_calib_examples: Optional[int] = None,
                   quantized_dtype: str = "int8", logger=None):
    """The reference's one-call workflow
    (`python/mxnet/contrib/quantization.py:423`): calibrate → rewrite →
    quantize params.  Returns (qsym, qarg_params, aux_params)."""
    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError("calib_mode must be none/naive/entropy")
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError("calib_data required for calib_mode=%r"
                             % calib_mode)
        ranges = calibrate_ranges(
            sym, arg_params, aux_params, calib_data,
            data_names=data_names, label_names=label_names,
            num_calib_examples=num_calib_examples, calib_mode=calib_mode,
            excluded_sym_names=excluded_sym_names)
    else:
        ranges = None  # dynamic: runtime min/max in _contrib_quantize_v2
    qsym, offline = quantize_symbol(
        sym, ranges, excluded_sym_names=excluded_sym_names,
        quantized_dtype=quantized_dtype)
    qargs = quantize_params(qsym, arg_params or {}, offline)
    return qsym, qargs, dict(aux_params or {})
