"""SVRG training module (reference
`python/mxnet/contrib/svrg_optimization/svrg_module.py`).

Stochastic Variance-Reduced Gradient (Johnson & Zhang, NeurIPS 2013):
every ``update_freq`` epochs a snapshot of the weights w~ is taken and
the FULL dataset gradient mu = (1/N) sum_i grad f_i(w~) is computed;
each minibatch step then descends along

    g_svrg = grad f_B(w) - grad f_B(w~) + mu

whose variance shrinks as w approaches w~, letting plain SGD use a
constant learning rate.

The reference maintains a shadow C++ module and splices a special
kvstore optimizer; here the snapshot is a second `Module` sharing the
same Symbol (each is ONE fused XLA step — forward+backward of batch B
at w and at w~ are two compiled calls), and the variance-reduced
gradient is assembled on-device before the normal optimizer update.
"""
from __future__ import annotations

import logging

from ...base import MXNetError
from ...module.module import Module

__all__ = ["SVRGModule"]


class SVRGModule(Module):
    """Module with SVRG gradient correction.

    Parameters match `Module`, plus ``update_freq``: the number of
    epochs between full-gradient snapshots (reference SVRGModule).
    """

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None, update_freq=None):
        super(SVRGModule, self).__init__(
            symbol, data_names=data_names, label_names=label_names,
            logger=logger, context=context,
            work_load_list=work_load_list,
            fixed_param_names=fixed_param_names, state_names=state_names,
            group2ctxs=group2ctxs, compression_params=compression_params)
        if not isinstance(update_freq, int) or update_freq < 1:
            raise MXNetError("update_freq must be a positive int (epochs "
                             "between full-gradient snapshots)")
        self.update_freq = update_freq
        # shadow module evaluating gradients at the snapshot weights w~;
        # MUST mirror every construction option that shapes the param
        # list, or the positional grad zip in _update_svrg_gradients
        # pairs different parameters
        self._mod_aux = Module(symbol, data_names=data_names,
                               label_names=label_names, logger=logger,
                               context=context,
                               work_load_list=work_load_list,
                               fixed_param_names=fixed_param_names,
                               state_names=state_names,
                               group2ctxs=group2ctxs,
                               compression_params=compression_params)
        self._param_dict = None   # mu: full gradient at w~, per param

    # -- lifecycle --------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        super(SVRGModule, self).bind(
            data_shapes, label_shapes, for_training, inputs_need_grad,
            force_rebind, shared_module, grad_req)
        if for_training:
            self._mod_aux.bind(data_shapes, label_shapes, for_training,
                               inputs_need_grad, force_rebind,
                               shared_module, grad_req)

    def init_params(self, *args, **kwargs):
        super(SVRGModule, self).init_params(*args, **kwargs)
        if self._mod_aux.binded:
            arg, aux = self.get_params()
            self._mod_aux.set_params(arg, aux, allow_missing=False,
                                     allow_extra=True)

    def reshape(self, data_shapes, label_shapes=None):
        super(SVRGModule, self).reshape(data_shapes, label_shapes)
        if self._mod_aux.binded:
            self._mod_aux.reshape(data_shapes, label_shapes)

    # -- per-batch path ---------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        super(SVRGModule, self).forward(data_batch, is_train)
        if is_train and self._mod_aux.binded:
            self._mod_aux.forward(data_batch, is_train=True)

    def backward(self, out_grads=None):
        super(SVRGModule, self).backward(out_grads)
        if self._mod_aux.binded:
            self._mod_aux.backward(out_grads)

    def update(self):
        if self._param_dict is not None:
            self._update_svrg_gradients()
        super(SVRGModule, self).update()

    def _update_svrg_gradients(self):
        """grad <- grad(w) - grad(w~) + mu, in place on the main
        module's gradient buffers (reference
        _svrg_grads_update_rule)."""
        eg = self._exec_group
        ag = self._mod_aux._exec_group
        for name, grads, aux_grads in zip(eg.param_names, eg.grad_arrays,
                                          ag.grad_arrays):
            mu = self._param_dict.get(name)
            if mu is None:
                continue
            ndev = sum(1 for g in grads if g is not None)
            for g, ga in zip(grads, aux_grads):
                if g is None or ga is None:
                    continue
                # mu is split across devices: per-device grads are SUMMED
                # by the update path, and mu must appear exactly once in
                # the aggregate
                g._set_jax(g._data - ga._data + mu._data / ndev)

    # -- snapshot ---------------------------------------------------------
    def update_full_grads(self, train_data):
        """Take the snapshot: copy w -> w~ and accumulate the mean full
        gradient mu over `train_data` (reference update_full_grads)."""
        if not self._mod_aux.binded:
            raise MXNetError("bind(for_training=True) first")
        arg, aux = self.get_params()
        self._mod_aux.set_params(arg, aux, allow_missing=False,
                                 allow_extra=True)
        accum = {}
        nbatch = 0
        train_data.reset()
        for batch in train_data:
            self._mod_aux.forward(batch, is_train=True)
            self._mod_aux.backward()
            ag = self._mod_aux._exec_group
            for name, grads in zip(ag.param_names, ag.grad_arrays):
                g = grads[0]
                if g is None:
                    continue
                total = g._data
                for extra in grads[1:]:
                    if extra is not None:
                        total = total + extra._data
                if name in accum:
                    accum[name] = accum[name] + total
                else:
                    accum[name] = total
            nbatch += 1
        if nbatch == 0:
            raise MXNetError("update_full_grads: empty data iterator")
        train_data.reset()  # leave the iterator ready for the epoch loop
        from ...ndarray.ndarray import NDArray

        self._param_dict = {
            name: NDArray(total / float(nbatch), _committed=True)
            for name, total in accum.items()}

    # -- training loop ----------------------------------------------------
    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """Module.fit with a full-gradient snapshot every
        ``update_freq`` epochs (reference SVRGModule.fit).

        The training loop itself is `BaseModule.fit`, run one epoch at a
        time so the snapshot can be injected between epochs — no
        duplicated loop to drift from the base implementation."""
        from ...initializer import Uniform

        if num_epoch is None:
            raise MXNetError("num_epoch is required for fit()")
        # bind + init up front (the base fit calls below then no-op)
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params)
                            if not isinstance(optimizer_params, dict)
                            else optimizer_params)
        for epoch in range(begin_epoch, num_epoch):
            if (epoch - begin_epoch) % self.update_freq == 0:
                self.update_full_grads(train_data)
            super(SVRGModule, self).fit(
                train_data, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=optimizer, optimizer_params=optimizer_params,
                eval_end_callback=eval_end_callback,
                eval_batch_end_callback=eval_batch_end_callback,
                initializer=initializer or Uniform(0.01),
                arg_params=None, aux_params=None, allow_missing=False,
                force_rebind=False, force_init=False, begin_epoch=epoch,
                num_epoch=epoch + 1,
                validation_metric=validation_metric, monitor=monitor)

    def prepare(self, data_batch, sparse_row_id_fn=None):
        super(SVRGModule, self).prepare(data_batch, sparse_row_id_fn)
        if self._mod_aux.binded:
            self._mod_aux.prepare(data_batch, sparse_row_id_fn)
