"""mx.xprof: measured per-op device-time attribution.

`mx.perf` (PR 10) attributes time at whole-PROGRAM granularity; this
module answers the next question — *which ops inside the program* —
with two acquisition paths feeding ONE schema:

* **Xplane ingestion** (:func:`ingest`): a minimal protobuf
  wire-format decoder (no TF/tsl dependency) for the XSpace files
  `mx.inspect.trace(dir)` / ``jax.profiler`` emit.  Device-line op
  events are extracted and joined back to model layers through the
  ``named_scope`` op_name metadata the graph builder plants in every
  HLO instruction (``jvp(layer)`` = forward, ``transpose(jvp(layer))``
  = backward/wgrad).  This is the ground-truth path: it reads what the
  device actually ran (post-fusion kernels), including idle gaps.

* **Timed eager replay** (:func:`profile`): the backend-portable
  fallback — the same NNVM topological walk `health.diagnose` runs
  (AMP casts and ``__rng_id__`` folding included) with
  ``block_until_ready`` per node.  Eager per-op dispatch is far slower
  than the fused compiled program, so the replay measures *relative*
  per-op shares and the absolute walls are CALIBRATED against the
  `mx.perf` sampled program wall (call→ready).  The calibrated sum
  therefore reconciles with the program wall by construction; what the
  guard (`tools/check_xprof.py`) proves is that the plumbing — perf
  wall, registry join, share math — stays consistent end to end.

Both paths land an ``OpProfile`` dict: per-op / per-layer /
per-op-class measured wall, joined against the `mx.inspect` registry's
cost analysis over the ``MXTPU_PEAK_*`` table → achieved
FLOPS/bandwidth, roofline placement, measured-vs-modeled discrepancy,
device-idle gaps, and a top-K-sinks report (:func:`report`,
``tools/op_report.py``).

Consumers: `mx.inspect` program records grow an ``op_profile`` field,
telemetry gets an ``op_profile`` event kind (cluster.json /
``tools/dash.py`` name each rank's top sink), `mx.tune` search priors
accept measured per-op times (`tune.search.cost_model_priors`), and
`bench_common` rows can carry the breakdown.

Env: ``MXTPU_XPROF`` (default 1) gates everything — disabled, every
entry point is one bool check; ``MXTPU_XPROF_EVERY=N`` auto-profiles
every Nth FusedTrainLoop chunk (default 0 = off);
``MXTPU_XPROF_TOPK`` sizes the top-sink list (default 10).
"""
from __future__ import annotations

import collections
import os
import re
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .base import MXNetError, getenv_bool

__all__ = [
    "enabled", "enable", "decode_xspace", "find_xplane_files",
    "ingest", "profile", "attach", "get", "last", "report",
    "format_report", "top_sink", "bench_breakdown", "classify",
    "maybe_autoprofile", "reset", "SCHEMA",
]

SCHEMA = "mxtpu-xprof-v1"

_ENABLED = getenv_bool("MXTPU_XPROF", True)
_AUTO_EVERY = int(os.environ.get("MXTPU_XPROF_EVERY", "0") or 0)
_TOP_K = max(1, int(os.environ.get("MXTPU_XPROF_TOPK", "10") or 10))

_lock = threading.Lock()
# latest OpProfile per inspect-registry program name + the most recent
_PROFILES: "collections.OrderedDict[str, Dict[str, Any]]" = \
    collections.OrderedDict()
_MAX_PROFILES = 32


def enabled() -> bool:
    return _ENABLED


def enable(on: bool = True) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def reset() -> None:
    with _lock:
        _PROFILES.clear()


# ---------------------------------------------------------------------------
# Protobuf wire-format decoder (XSpace subset, no TF/tsl dependency)
# ---------------------------------------------------------------------------
#
# Field numbers verified against jax 0.4.x profiler output:
#   XSpace.planes = 1
#   XPlane:  id=1 name=2 lines=3 event_metadata(map)=4
#            stat_metadata(map)=5 stats=6
#   XLine:   id=1 name=2 timestamp_ns=3 events=4 duration_ps=9
#   XEvent:  metadata_id=1 offset_ps=2 duration_ps=3 stats=4
#            num_occurrences=5
#   XStat:   metadata_id=1 double=2 uint64=3 int64=4 str=5 bytes=6
#            ref=7
#   XEventMetadata: id=1 name=2 metadata=3 display_name=4
#   XStatMetadata:  id=1 name=2
#   proto map entries: key=1 value=2
#
# Torn/truncated files must read as PARTIAL, never crash: every
# container loop catches _Truncated and keeps what it already decoded.


class _Truncated(Exception):
    """Internal: the buffer ended (or was malformed) mid-field."""


def _varint(buf, pos: int, end: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= end:
            raise _Truncated()
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 63:
            raise _Truncated()


def _iter_fields(buf, pos: int, end: int):
    """Yield (field_no, wire_type, value) until ``end``.  Length-
    delimited values come back as (start, stop) spans into ``buf`` —
    no copies.  Raises _Truncated on overrun/unknown wire types."""
    while pos < end:
        tag, pos = _varint(buf, pos, end)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            val, pos = _varint(buf, pos, end)
        elif wt == 1:
            if pos + 8 > end:
                raise _Truncated()
            val = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wt == 2:
            ln, pos = _varint(buf, pos, end)
            if ln < 0 or pos + ln > end:
                raise _Truncated()
            val = (pos, pos + ln)
            pos += ln
        elif wt == 5:
            if pos + 4 > end:
                raise _Truncated()
            val = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:
            # groups (3/4) and anything newer: cannot be skipped
            # safely without schema knowledge — treat as torn
            raise _Truncated()
        yield fno, wt, val


def _text(buf, span) -> str:
    s, e = span
    return bytes(buf[s:e]).decode("utf-8", "replace")


def _signed(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _dec_stat(buf, span) -> Dict[str, Any]:
    st: Dict[str, Any] = {}
    try:
        for fno, wt, val in _iter_fields(buf, *span):
            if fno == 1 and wt == 0:
                st["metadata_id"] = val
            elif fno == 2 and wt == 1:
                st["value"] = struct.unpack("<d", struct.pack("<Q",
                                                              val))[0]
            elif fno == 3 and wt == 0:
                st["value"] = val
            elif fno == 4 and wt == 0:
                st["value"] = _signed(val)
            elif fno == 5 and wt == 2:
                st["value"] = _text(buf, val)
            elif fno == 6 and wt == 2:
                st["value"] = bytes(buf[val[0]:val[1]])
            elif fno == 7 and wt == 0:
                st["ref"] = val
    except _Truncated:
        pass
    return st


def _dec_event(buf, span) -> Dict[str, Any]:
    ev: Dict[str, Any] = {"metadata_id": 0, "offset_ps": 0,
                          "duration_ps": 0, "stats": []}
    try:
        for fno, wt, val in _iter_fields(buf, *span):
            if fno == 1 and wt == 0:
                ev["metadata_id"] = val
            elif fno == 2 and wt == 0:
                ev["offset_ps"] = _signed(val)
            elif fno == 3 and wt == 0:
                ev["duration_ps"] = val
            elif fno == 4 and wt == 2:
                ev["stats"].append(_dec_stat(buf, val))
            elif fno == 5 and wt == 0:
                ev["num_occurrences"] = val
    except _Truncated:
        pass
    return ev


def _dec_line(buf, span) -> Dict[str, Any]:
    ln: Dict[str, Any] = {"name": "", "timestamp_ns": 0, "events": []}
    try:
        for fno, wt, val in _iter_fields(buf, *span):
            if fno == 1 and wt == 0:
                ln["id"] = val
            elif fno == 2 and wt == 2:
                ln["name"] = _text(buf, val)
            elif fno == 3 and wt == 0:
                ln["timestamp_ns"] = _signed(val)
            elif fno == 4 and wt == 2:
                ln["events"].append(_dec_event(buf, val))
            elif fno == 9 and wt == 0:
                ln["duration_ps"] = val
    except _Truncated:
        pass
    return ln


def _dec_event_metadata(buf, span) -> Dict[str, Any]:
    md: Dict[str, Any] = {"id": 0, "name": ""}
    try:
        for fno, wt, val in _iter_fields(buf, *span):
            if fno == 1 and wt == 0:
                md["id"] = val
            elif fno == 2 and wt == 2:
                md["name"] = _text(buf, val)
            elif fno == 4 and wt == 2:
                md["display_name"] = _text(buf, val)
    except _Truncated:
        pass
    return md


def _dec_stat_metadata(buf, span) -> Dict[str, Any]:
    md: Dict[str, Any] = {"id": 0, "name": ""}
    try:
        for fno, wt, val in _iter_fields(buf, *span):
            if fno == 1 and wt == 0:
                md["id"] = val
            elif fno == 2 and wt == 2:
                md["name"] = _text(buf, val)
    except _Truncated:
        pass
    return md


def _dec_map_entry(buf, span, value_decoder):
    key = None
    value = None
    try:
        for fno, wt, val in _iter_fields(buf, *span):
            if fno == 1 and wt == 0:
                key = val
            elif fno == 2 and wt == 2:
                value = value_decoder(buf, val)
    except _Truncated:
        pass
    if value is not None and key is None:
        key = value.get("id")
    return key, value


def _dec_plane(buf, span) -> Dict[str, Any]:
    pl: Dict[str, Any] = {"name": "", "lines": [],
                          "event_metadata": {}, "stat_metadata": {}}
    try:
        for fno, wt, val in _iter_fields(buf, *span):
            if fno == 1 and wt == 0:
                pl["id"] = val
            elif fno == 2 and wt == 2:
                pl["name"] = _text(buf, val)
            elif fno == 3 and wt == 2:
                pl["lines"].append(_dec_line(buf, val))
            elif fno == 4 and wt == 2:
                k, v = _dec_map_entry(buf, val, _dec_event_metadata)
                if k is not None and v is not None:
                    pl["event_metadata"][k] = v
            elif fno == 5 and wt == 2:
                k, v = _dec_map_entry(buf, val, _dec_stat_metadata)
                if k is not None and v is not None:
                    pl["stat_metadata"][k] = v
            elif fno == 6 and wt == 2:
                pl.setdefault("stats", []).append(_dec_stat(buf, val))
    except _Truncated:
        pass
    return pl


def decode_xspace(data: bytes) -> Dict[str, Any]:
    """Decode a serialized XSpace (``*.xplane.pb``) into plain dicts.
    Truncated input decodes to whatever prefix is intact — a torn
    profile read mid-write yields a partial space, never an
    exception."""
    buf = memoryview(data)
    space: Dict[str, Any] = {"planes": []}
    try:
        for fno, wt, val in _iter_fields(buf, 0, len(buf)):
            if fno == 1 and wt == 2:
                space["planes"].append(_dec_plane(buf, val))
    except _Truncated:
        space["truncated"] = True
    return space


def find_xplane_files(logdir: str) -> List[str]:
    """All ``*.xplane.pb`` files under ``logdir`` (the jax profiler
    writes ``plugins/profile/<ts>/<host>.xplane.pb``)."""
    out = []
    for root, _dirs, files in os.walk(logdir):
        for f in files:
            if f.endswith(".xplane.pb"):
                out.append(os.path.join(root, f))
    return sorted(out)


# ---------------------------------------------------------------------------
# Op classification + layer join
# ---------------------------------------------------------------------------

#: the op-class vocabulary of the report (docs/observability.md):
#: conv / matmul / bn / wgrad / copy / collective / reduce /
#: elementwise / optimizer / other
_COLLECTIVE_PAT = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective", "all-to-all", "psum")
#: exact HLO control-flow wrapper instruction names (`while`,
#: `while.3`, `conditional`, `call.2`) — their trace events CONTAIN
#: the body ops' events, so `ingest` must skip them
_CONTROL_WRAPPER_RE = re.compile(
    r"^(while|conditional|call)(\.\d+)?$")
_COPY_PAT = ("copy", "transpose", "reshape", "bitcast", "pad", "slice",
             "concatenate", "gather", "dynamic-update", "broadcast",
             "prefetch", "tuple", "convert", "iota")


def classify(name: str, layer: Optional[str] = None,
             direction: Optional[str] = None) -> str:
    """Op class of one kernel/op name (HLO instruction name on the
    xplane path, mxtpu op name on the replay path).  ``direction``
    ('fwd'/'bwd', from the op_name layer join) turns backward conv /
    matmul into the ``wgrad`` class."""
    n = (name or "").lower()
    hay = n + " " + (layer or "").lower()
    if any(p in n for p in _COLLECTIVE_PAT):
        return "collective"
    if "conv" in hay:
        return "wgrad" if direction == "bwd" else "conv"
    if "batchnorm" in hay or "batch_norm" in hay or "-norm" in n:
        return "bn"
    if "dot" in n or "fullyconnected" in hay or "dense" in hay \
            or "matmul" in n or "einsum" in n:
        return "wgrad" if direction == "bwd" else "matmul"
    if any(p in n for p in _COPY_PAT):
        return "copy"
    if "sgd" in hay or "adam" in hay or "optimizer" in hay:
        return "optimizer"
    if "reduce" in n or "sum" in n or "argmax" in n:
        return "reduce"
    if "fusion" in n or "loop" in n or "elemwise" in n or "add" in n \
            or "multiply" in n or "activation" in hay or "relu" in n \
            or "pool" in hay or "softmax" in hay or "dropout" in hay \
            or "exp" in n or "log" in n:
        return "elementwise"
    return "other"


_HLO_OPNAME_RE = re.compile(
    r"%?([\w.\-]+)\s*=\s*[^\n]*?op_name=\"([^\"]+)\"")
_SCOPE_JVP_RE = re.compile(r"transpose\(jvp\(([^()]+)\)\)|jvp\(([^()]+)\)")


def _layer_map_from_hlo(hlo_text: str) -> Dict[str, str]:
    """instruction name -> op_name metadata path, parsed from optimized
    HLO text (the `named_scope` attribution the graph builder plants)."""
    return {m.group(1): m.group(2)
            for m in _HLO_OPNAME_RE.finditer(hlo_text or "")}


def _layer_of(path: str) -> Tuple[Optional[str], Optional[str]]:
    """(layer, direction) from an op_name scope path: the DEEPEST
    ``jvp(layer)`` ('fwd') / ``transpose(jvp(layer))`` ('bwd') frame;
    plain scope paths fall back to their deepest named segment."""
    if not path:
        return None, None
    last = None
    for last in _SCOPE_JVP_RE.finditer(path):
        pass
    if last is not None:
        if last.group(1):
            return last.group(1), "bwd"
        return last.group(2), "fwd"
    parts = [p for p in path.split("/") if p and not p.startswith("jit(")]
    return (parts[-1] if parts else None), None


def _registry_hlo(program: Optional[str],
                  kind: Optional[str] = None) -> Optional[str]:
    """Optimized HLO text of a registered program's latest signature
    (None when unavailable — the join then degrades to no layers)."""
    if not program:
        return None
    try:
        from . import inspect as _insp

        rec = _insp.find(program)
        if rec is None:
            return None
        si = rec.latest_sig(kind)
        if si is None:
            return None
        return si.hlo_text()
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Path (a): xplane ingestion
# ---------------------------------------------------------------------------

def _is_device_line(plane_name: str, line_name: str) -> bool:
    """Lines that carry per-HLO-op device events: TPU/GPU device
    planes' op lines, and the CPU client's per-module lines
    (``tf_XLATfrtCpuClient/<id>``)."""
    if plane_name.startswith("/device:"):
        return "step" not in line_name.lower()
    return "xla" in line_name.lower()


def ingest(logdir: str, program: Optional[str] = None,
           kind: Optional[str] = None, steps: int = 1,
           module_filter: Optional[str] = None,
           calibrate: bool = True) -> Dict[str, Any]:
    """Build an OpProfile from the xplane files under ``logdir`` (a
    `mx.inspect.trace` output dir, or one ``.xplane.pb`` path).

    Device-line events are aggregated by op name, joined to layers via
    ``program``'s registered HLO op_name metadata, and normalized to
    per-step microseconds by ``steps`` (how many wall steps ran inside
    the trace).  ``module_filter`` keeps only events whose
    ``hlo_module`` stat contains the substring.  Raises MXNetError
    when the dir holds no xplane file at all."""
    files = [logdir] if os.path.isfile(logdir) \
        else find_xplane_files(logdir)
    if not files:
        raise MXNetError(
            "xprof.ingest: no .xplane.pb under %r — was the trace "
            "empty? (see mx.inspect.trace / EmptyTraceError)" % logdir)
    agg: Dict[str, List[float]] = {}   # name -> [total_us, count]
    modules: collections.Counter = collections.Counter()
    idle_us = 0.0
    span_us = 0.0
    truncated = False
    for path in files:
        with open(path, "rb") as f:
            space = decode_xspace(f.read())
        truncated = truncated or bool(space.get("truncated"))
        for plane in space["planes"]:
            smd = plane["stat_metadata"]
            stat_names = {k: v.get("name", "") for k, v in smd.items()}
            for line in plane["lines"]:
                if not _is_device_line(plane["name"], line["name"]):
                    continue
                t_min = None
                t_max = None
                busy_ps = 0
                for ev in line["events"]:
                    emd = plane["event_metadata"].get(ev["metadata_id"])
                    name = (emd or {}).get("name") or "?"
                    if "::" in name:
                        # C++ runtime frames (ThunkExecutor::Execute,
                        # ...) wrap the real op events on CPU client
                        # lines — framework overhead, not device ops
                        continue
                    if _CONTROL_WRAPPER_RE.match(name):
                        # control-flow wrapper instructions (the fused
                        # scan's `while`, conditionals, calls): their
                        # duration is the SUM of the body ops' spans,
                        # which are emitted as their own events on the
                        # same line — counting both double-books every
                        # microsecond of the loop body
                        continue
                    mod = None
                    for st in ev["stats"]:
                        sname = stat_names.get(st.get("metadata_id"), "")
                        if sname == "hlo_module":
                            ref = st.get("ref", st.get("value"))
                            mod = stat_names.get(ref, str(ref)) \
                                if isinstance(ref, int) else str(ref)
                    if mod:
                        modules[mod] += 1
                    if module_filter and mod \
                            and module_filter not in mod:
                        continue
                    dur = ev.get("duration_ps", 0)
                    off = ev.get("offset_ps", 0)
                    busy_ps += dur
                    t_min = off if t_min is None else min(t_min, off)
                    t_max = off + dur if t_max is None \
                        else max(t_max, off + dur)
                    cell = agg.setdefault(name, [0.0, 0])
                    cell[0] += dur / 1e6
                    cell[1] += ev.get("num_occurrences", 0) or 1
                if t_min is not None and t_max > t_min:
                    line_span = (t_max - t_min) / 1e6
                    span_us += line_span
                    idle_us += max(0.0, line_span - busy_ps / 1e6)
    layer_map = _layer_map_from_hlo(_registry_hlo(program, kind))
    steps = max(1, int(steps))
    ops = []
    for name, (us, count) in agg.items():
        path = layer_map.get(name)
        layer, direction = _layer_of(path) if path else (None, None)
        ops.append({
            "op": name,
            "wall_us": us / steps,
            "count": count,
            "layer": layer,
            "direction": direction,
            "op_class": classify(name, layer, direction),
        })
    prof = _assemble(ops, source="xplane", program=program, kind=kind,
                     steps=steps, idle_us=idle_us / steps,
                     calibrate=calibrate)
    if truncated:
        prof["truncated"] = True
    if modules:
        prof["hlo_modules"] = dict(modules.most_common(8))
    if program:
        attach(program, prof)
    return prof


# ---------------------------------------------------------------------------
# Path (b): timed eager replay
# ---------------------------------------------------------------------------

def _nbytes(v) -> int:
    try:
        return int(v.size) * v.dtype.itemsize
    except Exception:
        return 0


def _replay_walk(symbol, arg_names: Sequence[str],
                 aux_names: Sequence[str], arg_vals, aux_vals, key,
                 amp_dtype=None, train: bool = False,
                 repeat: int = 2) -> List[Dict[str, Any]]:
    """The timed eager walk: `health.diagnose`'s exact NNVM traversal
    (same AMP casts, same ``__rng_id__`` folding) with a warmup pass
    and ``repeat`` timed re-executions per node, ``block_until_ready``
    bounding each measurement (MIN across repeats — the node's
    intrinsic cost, not scheduler noise).  Returns one op row per
    non-variable node."""
    import jax

    from . import amp as _amp
    from . import inspect as _insp
    from .passes.graph import ensure_rng_ids, rng_id_of
    from .symbol.symbol import _topo_order

    ensure_rng_ids(symbol)
    nodes = _topo_order(symbol._outputs)
    arg_pos = {n: i for i, n in enumerate(arg_names)}
    aux_pos = {n: i for i, n in enumerate(aux_names)}
    env: Dict[Tuple[int, int], Any] = {}
    rows: List[Dict[str, Any]] = []
    rng_i = 0
    with _amp.scope(amp_dtype):
        for node in nodes:
            if node.is_variable:
                if node.is_aux:
                    val = aux_vals[aux_pos[node.name]]
                else:
                    val = arg_vals[arg_pos[node.name]]
                env[(id(node), 0)] = getattr(val, "_data", val)
                continue
            invals = [env[(id(inode), idx)]
                      for inode, idx in node.inputs]
            if amp_dtype is not None:
                invals = _amp.cast_op_inputs(node.op.name, invals,
                                             amp_dtype)
            attrs = dict(node.attrs)
            if node.op.train_aware:
                attrs["is_train"] = train
            if node.op.needs_rng:
                sub = jax.random.fold_in(key, rng_id_of(node, rng_i))
                rng_i += 1
                call = (lambda fn=node.op.fn, k=sub, iv=invals, at=attrs:
                        fn(k, *iv, **at))
            else:
                call = (lambda fn=node.op.fn, iv=invals, at=attrs:
                        fn(*iv, **at))
            # warmup: compiles the eager kernel and materializes the
            # outputs the downstream nodes consume
            out = call()
            jax.block_until_ready(out)
            best = float("inf")
            for _ in range(max(1, repeat)):
                t0 = time.perf_counter()
                jax.block_until_ready(call())
                best = min(best, time.perf_counter() - t0)
            if not isinstance(out, tuple):
                out = (out,)
            n_vis = node.op.n_outputs(node.attrs)
            if len(out) > n_vis and node.attrs.get("sub_aux"):
                out = out[:n_vis]
            for i, o in enumerate(out):
                env[(id(node), i)] = o
            in_shapes = [tuple(v.shape) for v in invals]
            in_dtypes = [v.dtype for v in invals]
            flops = _insp.op_flops(node, in_shapes, in_dtypes)
            nbytes = sum(_nbytes(v) for v in invals) + \
                sum(_nbytes(o) for o in out)
            rows.append({
                "op": node.name,
                "kernel": node.op.name,
                "wall_us": best * 1e6,
                "count": 1,
                "layer": node.name,
                "direction": "fwd",
                "op_class": classify(node.op.name, node.name, "fwd"),
                "flops": flops,
                "bytes": nbytes or None,
            })
    return rows


_BWD_FACTOR = 2.0  # standard fwd:bwd FLOP ratio (one fwd, ~two mults)


def _add_backward_rows(rows: List[Dict[str, Any]]) -> List[Dict]:
    """Synthetic backward rows for a TRAIN replay: the eager walk times
    the forward only, so each grad-producing node gets a
    ``(backward)`` row at ``_BWD_FACTOR``x its forward wall (flagged
    ``estimated`` — calibration against the measured program wall then
    scales fwd and bwd shares together).  conv/matmul backward lands
    in the ``wgrad`` class, matching the xplane join's
    ``transpose(jvp(...))`` attribution."""
    out = list(rows)
    for r in rows:
        cls = r["op_class"]
        if cls in ("copy", "collective", "optimizer"):
            continue
        out.append({
            "op": r["op"] + " (backward)",
            "kernel": r.get("kernel"),
            "wall_us": r["wall_us"] * _BWD_FACTOR,
            "count": r["count"],
            "layer": r["layer"],
            "direction": "bwd",
            "op_class": "wgrad" if cls in ("conv", "matmul") else cls,
            "flops": (r.get("flops") or 0) * _BWD_FACTOR or None,
            "bytes": r.get("bytes"),
            "estimated": True,
        })
    return out


def _program_wall_us(name: Optional[str]) -> Optional[float]:
    """Per-step measured program wall from the `mx.perf` observatory
    (sampled call→ready), the calibration target."""
    if not name:
        return None
    try:
        from . import perf as _perf

        row = _perf.programs(force=False).get(name)
        if not row:
            return None
        return row.get("wall_us_avg") or \
            row.get("device_compute_us_avg") or \
            row.get("host_dispatch_us_avg")
    except Exception:
        return None


def profile(target, data=None, kind: Optional[str] = None,
            key=None, repeat: int = 2, calibrate: bool = True,
            attach_result: bool = True) -> Optional[Dict[str, Any]]:
    """Timed-eager-replay OpProfile of a dispatch-path object:

    * **Executor** — replays its bound symbol over the CURRENT
      arg/aux arrays (set data via ``arg_dict`` first); train replay
      when it has differentiable args.
    * **CachedOp** — ``data`` = the full args list (NDArrays/arrays in
      ``list_arguments()`` order), plus aux via the op's usual flow;
      pass ``kind='train'`` for a train-step replay.
    * **FusedTrainLoop** — ``data`` = one batch per data slot (a list
      matching the loop's data slots; pass a staged (K, ...) stack's
      ``[0]`` slices).  Train replay with synthetic backward rows.
    * **Module** — delegates to its first executor.

    Returns the OpProfile (and attaches it to the program's
    `mx.inspect` record + telemetry), or None when ``MXTPU_XPROF=0``.
    Replay never dispatches the compiled program: zero retraces."""
    if not _ENABLED:
        return None
    import jax

    if key is None:
        key = jax.random.PRNGKey(0)
    # -- FusedTrainLoop -----------------------------------------------------
    if hasattr(target, "_jit_program") and hasattr(target, "_exec"):
        loop = target
        ex = loop._exec
        if data is None:
            raise MXNetError("xprof.profile(FusedTrainLoop) needs "
                             "data=[per-slot batch arrays] (e.g. "
                             "[s[0] for s in stack_batches(batches)])")
        full = [None] * len(loop._arg_names)
        for j, i in enumerate(loop._diff_idx):
            full[i] = loop._p_vals[j]
        for i in loop._fixed_idx:
            full[i] = ex.arg_arrays[i]._data
        for j, i in enumerate(loop._data_idx):
            v = data[j]
            full[i] = getattr(v, "_data", v)
        rows = _replay_walk(ex._symbol, loop._arg_names, ex._aux_names,
                            full, list(loop._aux_vals), key,
                            amp_dtype=ex._amp_dtype, train=True,
                            repeat=repeat)
        rows = _add_backward_rows(rows)
        name, kind = loop._insp.name, kind or "train"
    # -- Executor -----------------------------------------------------------
    elif hasattr(target, "arg_arrays") and hasattr(target, "_symbol"):
        ex = target
        train = kind != "infer" and bool(ex._diff_idx)
        rows = _replay_walk(ex._symbol, ex._arg_names, ex._aux_names,
                            list(ex.arg_arrays), list(ex.aux_arrays),
                            key, amp_dtype=ex._amp_dtype, train=train,
                            repeat=repeat)
        if train:
            rows = _add_backward_rows(rows)
        name, kind = ex._insp.name, kind or ("train" if train
                                             else "infer")
    # -- CachedOp -----------------------------------------------------------
    elif hasattr(target, "_jit_infer") and hasattr(target, "_arg_names"):
        cop = target
        if data is None:
            raise MXNetError("xprof.profile(CachedOp) needs data="
                             "[args in list_arguments() order]")
        args = list(data)
        n = len(cop._arg_names)
        aux = args[n:] if len(args) > n else []
        train = kind == "train"
        rows = _replay_walk(cop._symbol, cop._arg_names,
                            cop._aux_names, args[:n], aux, key,
                            amp_dtype=cop._amp_dtype, train=train,
                            repeat=repeat)
        if train:
            rows = _add_backward_rows(rows)
        name, kind = cop._insp.name, kind or ("train" if train
                                              else "infer")
    # -- Module -------------------------------------------------------------
    elif hasattr(target, "_exec_group"):
        return profile(target._exec_group.execs[0], data=data,
                       kind=kind, key=key, repeat=repeat,
                       calibrate=calibrate,
                       attach_result=attach_result)
    else:
        raise MXNetError("xprof.profile: unsupported target %r — pass "
                         "an Executor, CachedOp, FusedTrainLoop or "
                         "Module" % type(target).__name__)
    prof = _assemble(rows, source="replay", program=name, kind=kind,
                     steps=1, calibrate=calibrate)
    if attach_result:
        attach(name, prof)
    return prof


# ---------------------------------------------------------------------------
# The one schema + enrichment
# ---------------------------------------------------------------------------

def _assemble(ops: List[Dict[str, Any]], source: str,
              program: Optional[str], kind: Optional[str],
              steps: int = 1, idle_us: Optional[float] = None,
              calibrate: bool = True) -> Dict[str, Any]:
    """Normalize op rows into the OpProfile schema: shares, per-layer /
    per-class rollups, roofline enrichment over the ``MXTPU_PEAK_*``
    table, calibration against the `mx.perf` program wall, top-K."""
    from . import perf as _perf

    ops = [dict(o) for o in ops if o.get("wall_us", 0) > 0]
    raw_sum = sum(o["wall_us"] for o in ops)
    wall_us = _program_wall_us(program)
    calibration = None
    if calibrate and wall_us and raw_sum > 0:
        scale = wall_us / raw_sum
        for o in ops:
            o["raw_wall_us"] = o["wall_us"]
            o["wall_us"] = o["wall_us"] * scale
        calibration = {"program_wall_us": round(wall_us, 2),
                       "raw_sum_us": round(raw_sum, 2),
                       "scale": round(scale, 6)}
    total = sum(o["wall_us"] for o in ops) or 1.0
    pkf, pkb = _perf.peak_flops(), _perf.peak_bytes()
    layers: Dict[str, float] = collections.defaultdict(float)
    classes: Dict[str, float] = collections.defaultdict(float)
    for o in ops:
        o["share"] = o["wall_us"] / total
        if o.get("layer"):
            layers[o["layer"]] += o["wall_us"]
        classes[o.get("op_class") or "other"] += o["wall_us"]
        wall_s = o["wall_us"] / 1e6
        flops = o.get("flops")
        nbytes = o.get("bytes")
        if flops and wall_s > 0:
            o["achieved_gflops"] = round(flops / wall_s / 1e9, 3)
            o["pct_peak_flops"] = round(
                100.0 * flops / (wall_s * pkf), 2)
        if nbytes and wall_s > 0:
            o["achieved_gbps"] = round(nbytes / wall_s / 1e9, 3)
            o["pct_peak_bytes"] = round(
                100.0 * nbytes / (wall_s * pkb), 2)
        if flops and nbytes:
            rf = _perf.roofline(flops, nbytes)
            if rf is not None:
                o["bound"] = rf["bound"]
                # fraction of the roofline this op achieves on its
                # binding resource
                o["roofline_frac"] = round(min(
                    flops / (wall_s * pkf) if rf["bound"] == "compute"
                    else nbytes / (wall_s * pkb), 1.0), 4) \
                    if wall_s > 0 else None
            modeled_us = max(flops / pkf, nbytes / pkb) * 1e6
            if modeled_us > 0:
                o["modeled_us"] = round(modeled_us, 3)
                # >1 = measured slower than the roofline floor says it
                # must be: the optimization headroom
                o["discrepancy"] = round(o["wall_us"] / modeled_us, 2)
        o["wall_us"] = round(o["wall_us"], 3)
        if "raw_wall_us" in o:
            o["raw_wall_us"] = round(o["raw_wall_us"], 3)
        o["share"] = round(o["share"], 4)
    ops.sort(key=lambda o: -o["wall_us"])
    prof: Dict[str, Any] = {
        "schema": SCHEMA,
        "source": source,
        "program": program,
        "kind": kind,
        "ts": time.time(),
        "steps": steps,
        "n_ops": len(ops),
        "device_us": round(total if ops else 0.0, 2),
        "ops": ops,
        "layers": {k: round(v, 2) for k, v in sorted(
            layers.items(), key=lambda kv: -kv[1])},
        "op_classes": {k: round(v, 2) for k, v in sorted(
            classes.items(), key=lambda kv: -kv[1])},
    }
    if wall_us is not None:
        prof["program_wall_us"] = round(wall_us, 2)
    if calibration is not None:
        prof["calibration"] = calibration
    if idle_us is not None:
        prof["idle_us"] = round(idle_us, 2)
    prof["top"] = ops[:_TOP_K]
    return prof


# ---------------------------------------------------------------------------
# Registry of latest profiles + consumer wiring
# ---------------------------------------------------------------------------

def attach(program: str, prof: Dict[str, Any]) -> None:
    """Record ``prof`` as the program's latest OpProfile: module
    registry (for :func:`report`/:func:`top_sink`), the program's
    `mx.inspect` record ``op_profile`` field (compact), and one
    telemetry ``op_profile`` event naming the top sink."""
    with _lock:
        _PROFILES[program] = prof
        _PROFILES.move_to_end(program)
        while len(_PROFILES) > _MAX_PROFILES:
            _PROFILES.popitem(last=False)
    try:
        from . import inspect as _insp

        rec = _insp.find(program)
        if rec is not None:
            rec.op_profile = _compact(prof)
    except Exception:
        pass
    try:
        from . import telemetry as _tel

        top = prof["ops"][0] if prof.get("ops") else None
        _tel.record("op_profile", program=program,
                    source=prof.get("source"),
                    step=_tel.current_step(),
                    n_ops=prof.get("n_ops"),
                    device_us=prof.get("device_us"),
                    idle_us=prof.get("idle_us"),
                    top_op=top and top["op"],
                    top_class=top and top.get("op_class"),
                    top_share=top and top.get("share"),
                    op_classes=prof.get("op_classes"))
    except Exception:
        pass


def _compact(prof: Dict[str, Any], k: int = 5) -> Dict[str, Any]:
    """The small form consumers embed (inspect records, ledger rows):
    totals + rollups + top-k ops, never the full op list."""
    return {key: prof.get(key) for key in
            ("schema", "source", "kind", "ts", "n_ops", "device_us",
             "program_wall_us", "idle_us", "op_classes")} | \
        {"top": [{f: o.get(f) for f in
                  ("op", "op_class", "layer", "wall_us", "share",
                   "bound", "discrepancy")}
                 for o in prof.get("top", [])[:k]]}


def get(program: str) -> Optional[Dict[str, Any]]:
    with _lock:
        return _PROFILES.get(program)


def last() -> Optional[Dict[str, Any]]:
    """The most recently attached OpProfile."""
    with _lock:
        return next(reversed(_PROFILES.values())) if _PROFILES else None


def top_sink() -> Optional[Dict[str, Any]]:
    """The top device-time sink of the latest profile — what
    `mx.obs`'s sampler/cluster view and ``tools/dash.py`` surface per
    rank.  Read-only: a dict lookup, never profiles."""
    prof = last()
    if not prof or not prof.get("ops"):
        return None
    t = prof["ops"][0]
    return {"program": prof.get("program"), "op": t["op"],
            "op_class": t.get("op_class"), "layer": t.get("layer"),
            "share": t.get("share"), "wall_us": t.get("wall_us")}


def bench_breakdown(prof: Optional[Dict[str, Any]] = None,
                    k: int = 5) -> Optional[Dict[str, Any]]:
    """The compact breakdown `bench_common` rows carry under
    ``--profile``: per-op-class us + top-k sinks (ledger-diffable by
    ``tools/compare_runs.py``)."""
    prof = prof or last()
    if not prof:
        return None
    return _compact(prof, k=k)


# ---------------------------------------------------------------------------
# Reports
# ---------------------------------------------------------------------------

def report(program: Optional[str] = None,
           k: Optional[int] = None) -> Dict[str, Any]:
    """The latest OpProfile (of ``program``, default most recent) with
    its top-``k`` sinks — raises when nothing was profiled yet."""
    prof = get(program) if program else last()
    if prof is None:
        raise MXNetError("xprof.report: no op profile recorded yet — "
                         "run mx.xprof.profile(...) or "
                         "mx.xprof.ingest(trace_dir)")
    if k:
        prof = dict(prof)
        prof["top"] = prof["ops"][:k]
    return prof


def format_report(prof: Dict[str, Any], k: int = 10) -> str:
    """Human-readable top-K-sinks table of one OpProfile."""
    lines = []
    cal = prof.get("calibration")
    lines.append(
        "op profile [%s] program=%s kind=%s  ops=%d  device=%.1fus%s%s"
        % (prof.get("source"), prof.get("program"), prof.get("kind"),
           prof.get("n_ops", 0), prof.get("device_us", 0.0),
           "  idle=%.1fus" % prof["idle_us"]
           if prof.get("idle_us") is not None else "",
           "  (calibrated to program wall %.1fus)"
           % cal["program_wall_us"] if cal else ""))
    classes = prof.get("op_classes") or {}
    total = sum(classes.values()) or 1.0
    lines.append("by class: " + "  ".join(
        "%s %.0f%%" % (c, 100.0 * v / total)
        for c, v in list(classes.items())[:6]))
    top = prof.get("ops", [])[:k]
    cum = 0.0
    lines.append("%-34s %-10s %-24s %9s %6s %6s %9s %9s %6s" % (
        "op", "class", "layer", "wall(us)", "share", "cum%",
        "GFLOP/s", "GB/s", "x-min"))
    for o in top:
        cum += o.get("share", 0.0)
        lines.append("%-34s %-10s %-24s %9.2f %5.1f%% %5.1f%% %9s %9s "
                     "%6s" % (
                         o["op"][:34], o.get("op_class", "-"),
                         (o.get("layer") or "-")[:24], o["wall_us"],
                         100.0 * o.get("share", 0.0), 100.0 * cum,
                         "%.2f" % o["achieved_gflops"]
                         if o.get("achieved_gflops") is not None
                         else "-",
                         "%.2f" % o["achieved_gbps"]
                         if o.get("achieved_gbps") is not None else "-",
                         "%.1f" % o["discrepancy"]
                         if o.get("discrepancy") is not None else "-"))
    if top:
        head = top[0]
        lines.append(
            "top sink: %s (%s%s) — %.1f%% of device time%s" % (
                head["op"], head.get("op_class"),
                ", %s" % head["layer"] if head.get("layer") else "",
                100.0 * head.get("share", 0.0),
                ", %s-bound at %.0f%% of roofline"
                % (head["bound"], 100.0 * head["roofline_frac"])
                if head.get("bound") and head.get("roofline_frac")
                is not None else ""))
    return "\n".join(lines)


def summary() -> str:
    prof = last()
    return format_report(prof) if prof else "no op profile recorded"


# ---------------------------------------------------------------------------
# FusedTrainLoop auto-profile hook
# ---------------------------------------------------------------------------

_auto_counts: Dict[int, int] = {}


def maybe_autoprofile(loop, data_stack) -> None:
    """Per-chunk hook `FusedTrainLoop.run_stacked` calls: every
    ``MXTPU_XPROF_EVERY`` chunks, replay-profile the loop on the first
    batch of the staged stack.  Default off; disabled/off mode is the
    two leading int/bool checks (<10us/step budget, asserted by
    ``tools/check_xprof.py``)."""
    if _AUTO_EVERY <= 0 or not _ENABLED:
        return
    key = id(loop)
    n = _auto_counts.get(key, 0) + 1
    _auto_counts[key] = n
    if n % _AUTO_EVERY:
        return
    try:
        profile(loop, data=[s[0] for s in data_stack])
    except Exception:
        pass
