"""Autograd: imperative differentiation with a host-side tape.

TPU-native re-design of the reference's `src/imperative/imperative.cc`
(RecordOp/Backward) and `python/mxnet/autograd.py`.  The reference tapes
NNVM nodes into `NDArray::entry_` and runs `pass::Gradient` to build a
backward graph executed node-by-node through the engine
(`imperative.cc:191,278`).  Here each recorded op captures a `jax.vjp`
closure (XLA computes the op-level gradient — the analog of per-op
FGradient), and `backward()` walks the tape in reverse topological order
accumulating cotangents.  The user-facing API (`record/pause/train_mode/
predict_mode`, `mark_variables`, `backward`, `grad`) matches the
reference's `python/mxnet/autograd.py:122-365`.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import MXNetError

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "get_symbol",
]


class _AGState(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False


_STATE = _AGState()


def is_recording() -> bool:
    return _STATE.recording


def is_training() -> bool:
    return _STATE.training


def set_recording(flag: bool) -> bool:
    prev, _STATE.recording = _STATE.recording, bool(flag)
    return prev


def set_training(flag: bool) -> bool:
    prev, _STATE.training = _STATE.training, bool(flag)
    return prev


class _RecordingScope(object):
    """Scope manager flipping recording/training flags
    (reference: `python/mxnet/autograd.py:40-120`)."""

    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec = recording
        self._train = training
        self._prev_rec = None
        self._prev_train = None

    def __enter__(self):
        if self._rec is not None:
            self._prev_rec = set_recording(self._rec)
        if self._train is not None:
            self._prev_train = set_training(self._train)
        return self

    def __exit__(self, *args):
        if self._rec is not None:
            set_recording(self._prev_rec)
        if self._train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True):
    return _RecordingScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


# ---------------------------------------------------------------------------
# Tape
# ---------------------------------------------------------------------------

class TapeNode(object):
    """One recorded op: vjp closure + graph wiring.

    ``input_entries[i]`` is ``("node", producer, out_idx)`` when input i was
    produced by an earlier recorded op, ``("leaf", ndarray)`` when it is a
    marked variable, or ``None`` for constants.
    """

    __slots__ = (
        "op_name",
        "vjp_fn",
        "input_entries",
        "out_avals",
        "n_outputs",
        "saved",
        "fwd",
    )

    def __init__(self, op_name, vjp_fn, input_entries, out_avals,
                 fwd=None):
        self.op_name = op_name
        self.vjp_fn = vjp_fn
        self.input_entries = input_entries
        self.out_avals = out_avals  # list of (shape, dtype)
        self.n_outputs = len(out_avals)
        self.saved = None
        # (tupled_fn, jax_inputs): the primal computation, kept so
        # grad(create_graph=True) can REPLAY the subgraph as a pure jax
        # function and differentiate the differentiation.  Trade-off:
        # this pins input buffers that cheap-op vjps (add/reshape/...)
        # would not retain; backward(retain_graph=False) frees it with
        # the residuals, and the node dies with its output NDArrays
        # otherwise
        self.fwd = fwd


def _record_fn(name, tupled_fn, nd_inputs, jax_inputs):
    """Run `tupled_fn` (returns a tuple of arrays) under jax.vjp and tape
    it.  Used both for single ops and for whole traced graphs (CachedOp).
    Returns (jax outputs tuple, node_or_None)."""
    import jax

    outs, vjp_fn = jax.vjp(tupled_fn, *jax_inputs)

    entries = []
    tracked = False
    for x in nd_inputs:
        ent = getattr(x, "_entry", None)
        if ent is not None:
            entries.append(("node", ent[0], ent[1]))
            tracked = True
        elif getattr(x, "_marked", False):
            entries.append(("leaf", x))
            tracked = True
        else:
            entries.append(None)

    if not tracked:
        # nothing upstream requires grad — don't tape
        return outs, None

    out_avals = [(tuple(o.shape), o.dtype) for o in outs]
    node = TapeNode(name, vjp_fn, entries, out_avals,
                    fwd=(tupled_fn, tuple(jax_inputs)))
    return outs, node


class SparseCot(object):
    """Row-sparse cotangent flowing through the tape: `indices` (k,)
    int32, sorted, padded at the tail with the OUT-OF-RANGE id
    `full_shape[0]` (zero rows; jax scatters drop them) + `values`
    (k, dim).  The TPU-native embedding-gradient
    form (reference: Embedding sparse_grad emits a RowSparseNDArray
    grad, `src/operator/tensor/indexing_op.cc` EmbeddingOpBackwardEx):
    static shapes (k = number of looked-up ids), no vocab-sized buffer
    ever materializes."""

    __slots__ = ("indices", "values", "full_shape")

    def __init__(self, indices, values, full_shape):
        self.indices = indices
        self.values = values
        self.full_shape = tuple(full_shape)

    @property
    def dtype(self):
        return self.values.dtype

    def densify(self):
        import jax.numpy as jnp

        out = jnp.zeros(self.full_shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def __add__(self, other):
        import jax.numpy as jnp

        if isinstance(other, SparseCot):
            # re-dedup so the sorted-unique+OOB-padding invariant holds
            # for consumers (scatter kernels use .set, so duplicate rows
            # would drop contributions)
            return _dedup_sparse_cot(
                jnp.concatenate([self.indices, other.indices]),
                jnp.concatenate([self.values, other.values]),
                self.full_shape[0])
        return self.densify() + other

    __radd__ = __add__


_EMB_FWD = None


def _emb_fwd_jit():
    """Cached jitted embedding gather (clip mode matches
    ops/indexing.py _embedding) — a fresh jax.jit per step would
    recompile the hottest op every batch."""
    global _EMB_FWD
    if _EMB_FWD is None:
        import jax
        import jax.numpy as jnp

        def fwd(d, w):
            idx = jnp.clip(d.astype(jnp.int32), 0, w.shape[0] - 1)
            return jnp.take(w, idx, axis=0)

        _EMB_FWD = jax.jit(fwd)
    return _EMB_FWD


_DEDUP_JIT = None


def _dedup_sparse_cot(idx, vals, n_rows):
    """(possibly-duplicated) scatter rows -> SparseCot with sorted
    unique indices, OOB tail padding (see SparseCot).  Static shapes:
    k = idx.size regardless of duplicate count.  One jitted kernel —
    unique/searchsorted/segment-sum fuse into a single dispatch."""
    global _DEDUP_JIT
    import jax

    if _DEDUP_JIT is None:
        import jax.numpy as jnp

        def kern(idx, vals, n_rows):
            k = idx.shape[0]
            uniq = jnp.unique(idx, size=k, fill_value=n_rows)
            pos = jnp.searchsorted(uniq, idx)
            seg = jax.ops.segment_sum(vals, pos, num_segments=k)
            return uniq, seg

        _DEDUP_JIT = jax.jit(kern, static_argnums=2)
    uniq, seg = _DEDUP_JIT(idx, vals, int(n_rows))
    return SparseCot(uniq, seg, (n_rows,) + tuple(vals.shape[1:]))


def _record_embedding_sparse(opdef, nd_inputs, jax_inputs, attrs, rng_key):
    """Tape an Embedding lookup whose weight cotangent stays row-sparse.
    Forward is the ordinary gather; the hand-written vjp deduplicates
    ids via fixed-size unique + segment-sum — O(k·dim), never O(vocab)."""
    import jax
    import jax.numpy as jnp

    data, weight = jax_inputs
    vocab, dim = weight.shape

    out = _emb_fwd_jit()(data, weight)

    def vjp_fn(cots):
        (og,) = cots
        # clip like the forward does (ops/indexing.py _embedding), so
        # out-of-range ids send gradient to the same clamped row on both
        # the sparse and dense paths
        idx = jnp.clip(data.astype(jnp.int32), 0, vocab - 1).reshape(-1)
        vals = og.reshape(-1, dim)
        # fixed-size unique + segment-sum (XLA-static).  Padding slots
        # get index `vocab` — OUT of range, which keeps the array sorted
        # (so the searchsorted position map is correct) and makes every
        # sparse consumer drop the padding for free: jax scatters
        # discard out-of-bounds rows, and host-side retain/searchsorted
        # paths see them past the last valid row.
        return (None, _dedup_sparse_cot(idx, vals, vocab))

    entries = []
    tracked = False
    for x in nd_inputs:
        ent = getattr(x, "_entry", None)
        if ent is not None:
            entries.append(("node", ent[0], ent[1]))
            tracked = True
        elif getattr(x, "_marked", False):
            entries.append(("leaf", x))
            tracked = True
        else:
            entries.append(None)
    if not tracked:
        return (out,), None
    node = TapeNode(opdef.name, vjp_fn, entries,
                    [(tuple(out.shape), out.dtype)],
                    fwd=(lambda d, w: (_emb_fwd_jit()(d, w),),
                         (data, weight)))
    return (out,), node


def _record_op(opdef, nd_inputs, jax_inputs, attrs: Dict[str, Any], rng_key=None):
    """Run op under jax.vjp and tape it. Returns (jax outputs tuple, node).

    The forward runs through the per-op jitted executable (jax.vjp of a
    jit-wrapped fn keeps the compiled call; the transpose compiles too) —
    so even taped eager ops execute as compiled XLA, matching the
    reference's kernel-per-op execution."""
    from .ops.registry import _jitted, canonical_attrs

    if (opdef.name == "Embedding" and attrs.get("sparse_grad")) or \
            opdef.name == "_contrib_SparseEmbedding":
        return _record_embedding_sparse(opdef, nd_inputs, jax_inputs,
                                        attrs, rng_key)
    fn = _jitted(opdef.name, canonical_attrs(attrs))

    if opdef.needs_rng:
        def closed(*xs):
            return fn(rng_key, *xs)
    else:
        closed = fn

    def tupled(*xs):
        out = closed(*xs)
        return out if isinstance(out, tuple) else (out,)

    return _record_fn(opdef.name, tupled, nd_inputs, jax_inputs)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to variables
    (reference: `python/mxnet/autograd.py:197`)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, gradbuf, req in zip(variables, gradients, grad_reqs):
        var._marked = req != "null"
        var._grad = gradbuf
        var._grad_req = req
        var._entry = None


# ---------------------------------------------------------------------------
# Backward walk
# ---------------------------------------------------------------------------

def _toposort(head_nodes: Sequence[TapeNode]) -> List[TapeNode]:
    order: List[TapeNode] = []
    state: Dict[int, int] = {}  # id -> 0 visiting, 1 done
    stack: List[Tuple[TapeNode, bool]] = [(n, False) for n in head_nodes if n is not None]
    while stack:
        node, processed = stack.pop()
        nid = id(node)
        if processed:
            state[nid] = 1
            order.append(node)
            continue
        if nid in state:
            continue
        state[nid] = 0
        stack.append((node, True))
        for ent in node.input_entries:
            if ent is not None and ent[0] == "node" and id(ent[1]) not in state:
                stack.append((ent[1], False))
    return order  # topological (inputs before consumers)


def _is_float_dtype(dt) -> bool:
    return np.issubdtype(np.dtype(dt), np.floating) or "bfloat16" in str(dt)


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of ``heads`` w.r.t. marked variables, accumulating
    into their ``.grad`` buffers (reference: `python/mxnet/autograd.py:243`,
    `Imperative::Backward` `src/imperative/imperative.cc:278`)."""
    from .ndarray.sparse import RowSparseNDArray
    from .ndarray.ndarray import NDArray as _ND

    grads = _run_backward(heads, head_grads, retain_graph)
    for var, g in grads.items():
        req = getattr(var, "_grad_req", "write")
        if var._grad is None:
            continue
        if isinstance(var._grad, RowSparseNDArray):
            if req == "add":
                raise MXNetError("grad_req='add' is not supported for "
                                 "row_sparse gradients (reference parity)")
            if isinstance(g, SparseCot):
                var._grad._set_jax(g.values.astype(var._grad.dtype))
                var._grad._aux = (_ND(g.indices, ctx=var._grad.ctx),)
                var._grad._shape = g.full_shape
            else:  # a dense path also touched this leaf
                from .ndarray.sparse import cast_storage as _cast

                dense = _ND(g, ctx=var._grad.ctx, _committed=True)
                rsp = _cast(dense, "row_sparse")
                var._grad._set_jax(rsp._data)
                var._grad._aux = rsp._aux
                var._grad._shape = rsp._shape
            continue
        if isinstance(g, SparseCot):
            g = g.densify()
        if req == "add":
            var._grad._set_jax(var._grad._data + g)
        else:
            var._grad._set_jax(g.astype(var._grad.dtype) if g.dtype != var._grad.dtype else g)


def _build_replay(heads, variables):
    """Rebuild the recorded subgraph from its leaves to `heads` as a
    PURE jax function.  Returns (replay, other_leaves): replay takes
    (var_vals, other_vals) — values for `variables` and for every OTHER
    tracked leaf of the subgraph.  Keeping the other leaves as function
    arguments (not captured constants) is what lets the outer backward
    differentiate the gradient w.r.t. them (e.g. a gradient penalty's
    dependence on the weights).  Powers grad(create_graph=True)."""
    head_nodes = [h._entry[0] for h in heads
                  if getattr(h, "_entry", None) is not None]
    order = _toposort(head_nodes)
    for node in order:
        if node.fwd is None:
            raise MXNetError(
                "create_graph=True: op %r was recorded without a "
                "replayable forward (or its graph was already freed by "
                "a retain_graph=False backward)" % node.op_name)
    # duplicates in `variables` share ONE replay slot (the first); the
    # caller-facing gradient is replicated per position afterwards —
    # the plain path gives every duplicate the full gradient
    var_pos = {}
    for i, v in enumerate(variables):
        var_pos.setdefault(id(v), i)
    # an INTERMEDIATE variable (has a producer entry) is treated as an
    # independent input at every consumption site — d(head)/d(t) holds
    # t's producers fixed, matching the plain path's semantics
    var_entry_pos = {}
    for i, v in enumerate(variables):
        ent = getattr(v, "_entry", None)
        if ent is not None:
            var_entry_pos.setdefault((id(ent[0]), ent[1]), i)
    other_leaves = []
    other_pos = {}
    for node in order:
        for ent in node.input_entries:
            if ent is not None and ent[0] == "leaf":
                v = ent[1]
                if id(v) not in var_pos and id(v) not in other_pos:
                    other_pos[id(v)] = len(other_leaves)
                    other_leaves.append(v)

    def replay(var_vals, other_vals):
        env = {}

        def entry_val(ent, captured):
            if ent is None:
                return captured
            if ent[0] == "leaf":
                v = ent[1]
                if id(v) in var_pos:
                    return var_vals[var_pos[id(v)]]
                return other_vals[other_pos[id(v)]]
            _, producer, idx = ent
            vpos = var_entry_pos.get((id(producer), idx))
            if vpos is not None:
                return var_vals[vpos]
            return env[id(producer)][idx]

        for node in order:
            fwd_fn, captured = node.fwd
            vals = [entry_val(e, c)
                    for e, c in zip(node.input_entries, captured)]
            env[id(node)] = fwd_fn(*vals)
        outs = []
        for h in heads:
            ent = getattr(h, "_entry", None)
            if ent is None:
                # a marked-leaf head: differentiable iff it IS one of
                # the variables; otherwise a constant (zero gradients,
                # matching the plain path)
                if id(h) in var_pos:
                    outs.append(var_vals[var_pos[id(h)]])
                elif id(h) in other_pos:
                    outs.append(other_vals[other_pos[id(h)]])
                else:
                    outs.append(h._data)
            else:
                vpos = var_entry_pos.get((id(ent[0]), ent[1]))
                outs.append(var_vals[vpos] if vpos is not None
                            else env[id(ent[0])][ent[1]])
        return tuple(outs)

    return replay, other_leaves


def _grad_create_graph(heads, variables, head_grads):
    """Differentiable gradients: replay the subgraph, vjp it, and TAPE
    the whole gradient computation as one node — so the returned
    gradients can themselves be backprop'd (higher-order autograd,
    reference tests/python/unittest/test_higher_order_grad.py)."""
    import jax
    import jax.numpy as jnp

    from .ndarray import NDArray

    for h in heads:
        if getattr(h, "_entry", None) is None \
                and not getattr(h, "_marked", False):
            raise MXNetError(
                "cannot differentiate a head that was not computed "
                "under autograd.record()")
    replay, other_leaves = _build_replay(heads, variables)
    n_var = len(variables)
    n_other = len(other_leaves)
    # head_grads ride as traced ARGUMENTS (not captured constants) so a
    # seed that itself depends on tracked values keeps its gradient
    # path in the outer backward
    hg_arrays = [hg for hg in head_grads if hg is not None]

    canon = {}
    for i, v in enumerate(variables):
        canon.setdefault(id(v), i)
    canon_of = [canon[id(v)] for v in variables]

    def grad_fn(*vals):
        var_vals = vals[:n_var]
        other_vals = vals[n_var:n_var + n_other]
        hg_vals = list(vals[n_var + n_other:])
        seeds = tuple(
            (hg_vals.pop(0) if hg is not None
             else jnp.ones(h.shape, dtype=h.dtype))
            for h, hg in zip(heads, head_grads))
        _, vjp = jax.vjp(lambda *vv: replay(vv, other_vals), *var_vals)
        gs = vjp(seeds)
        # duplicates: every position of the same variable reports the
        # full gradient (replay routed all reads to the canonical slot)
        return tuple(gs[canon_of[i]] for i in range(n_var))

    all_inputs = list(variables) + list(other_leaves) + hg_arrays
    outs, node = _record_fn("_grad", grad_fn, all_inputs,
                            [v._data for v in all_inputs])
    result = []
    for i, g in enumerate(outs):
        arr = NDArray(g, ctx=variables[i].ctx, _committed=True)
        if node is not None:
            arr._entry = (node, i)
        result.append(arr)
    return result


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return gradients of heads w.r.t. variables without touching ``.grad``
    (reference: `python/mxnet/autograd.py:270`).  With ``create_graph``
    the gradient computation itself is taped (replay + vjp), so the
    results support another backward — higher-order autograd."""
    from .ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
    for v in variables:
        if not getattr(v, "_marked", False) and getattr(v, "_entry", None) is None:
            raise MXNetError(
                "one of the variables was not used in the graph or not marked "
                "with attach_grad/mark_variables"
            )
    if create_graph:
        if isinstance(heads, NDArray):
            heads = [heads]
        if head_grads is None:
            head_grads = [None] * len(heads)
        elif isinstance(head_grads, NDArray):
            head_grads = [head_grads]
        if len(heads) != len(head_grads):
            raise MXNetError("heads and head_grads length mismatch")
        return _grad_create_graph(heads, variables, head_grads)
    gmap = _run_backward(heads, head_grads,
                         retain_graph=bool(retain_graph),
                         extra_vars=variables)
    out = []
    for v in gmap["__vars__"]:
        out.append(v)
    return out


def _run_backward(heads, head_grads=None, retain_graph=False, extra_vars=None):
    import jax.numpy as jnp

    from .ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]
    if len(heads) != len(head_grads):
        raise MXNetError("heads and head_grads length mismatch")

    head_nodes = []
    for h in heads:
        ent = getattr(h, "_entry", None)
        if ent is None and not getattr(h, "_marked", False):
            raise MXNetError(
                "cannot differentiate a head that was not computed under "
                "autograd.record()"
            )
        if ent is not None:
            head_nodes.append(ent[0])

    order = _toposort(head_nodes)
    # cotangent store: id(node) -> [per-output cotangent or None]
    cots: Dict[int, List[Optional[Any]]] = {id(n): [None] * n.n_outputs for n in order}
    leaf_grads: Dict[Any, Any] = {}

    def add_leaf(var, g):
        if var in leaf_grads:
            leaf_grads[var] = leaf_grads[var] + g
        else:
            leaf_grads[var] = g

    # seed heads
    for h, hg in zip(heads, head_grads):
        ent = getattr(h, "_entry", None)
        seed = hg._data if hg is not None else jnp.ones(h.shape, dtype=h.dtype)
        if ent is None:
            add_leaf(h, seed)  # head IS a marked leaf
            continue
        node, idx = ent
        slot = cots[id(node)]
        slot[idx] = seed if slot[idx] is None else slot[idx] + seed

    # reverse sweep
    for node in reversed(order):
        slot = cots[id(node)]
        if all(c is None for c in slot):
            continue
        if node.vjp_fn is None:
            raise MXNetError(
                "the backward graph has already been freed; call backward("
                "retain_graph=True) to backprop through it a second time")
        full = []
        for c, (shape, dtype) in zip(slot, node.out_avals):
            if isinstance(c, SparseCot):
                c = c.densify()  # upstream vjps consume dense arrays
            full.append(c if c is not None else jnp.zeros(shape, dtype=dtype))
        in_cots = node.vjp_fn(tuple(full))
        for ent, g in zip(node.input_entries, in_cots):
            if ent is None or g is None:
                continue
            # drop symbolic-zero / int cotangents (non-diff inputs)
            if hasattr(g, "dtype") and not _is_float_dtype(g.dtype):
                continue
            if ent[0] == "node":
                pslot = cots[id(ent[1])]
                pslot[ent[2]] = g if pslot[ent[2]] is None else pslot[ent[2]] + g
            else:
                add_leaf(ent[1], g)
        if not retain_graph:
            node.vjp_fn = None  # free residuals
            node.fwd = None     # and the replay closure's pinned inputs

    if extra_vars is not None:
        from .ndarray import NDArray as _ND

        res = []
        for v in extra_vars:
            g = leaf_grads.get(v)
            if g is None:
                # variable recorded mid-graph (non-leaf): collect from node
                # slot; unreachable-from-heads variables get zeros
                ent = getattr(v, "_entry", None)
                if ent is not None and id(ent[0]) in cots:
                    g = cots[id(ent[0])][ent[1]]
            if g is None:
                g = jnp.zeros(v.shape, dtype=v.dtype)
            if isinstance(g, SparseCot):
                from .ndarray.sparse import RowSparseNDArray as _RSP

                res.append(_RSP(g.values, (g.indices,), g.full_shape,
                                ctx=v.ctx))
                continue
            res.append(_ND(g, ctx=v.ctx))
        return {"__vars__": res}
    return leaf_grads


def get_symbol(x):  # pragma: no cover - parity stub
    raise MXNetError("autograd.get_symbol is not supported; use hybridize()")


class Function(object):
    """Custom differentiable function (reference
    `python/mxnet/autograd.py:365`): subclass with `forward(*inputs)` and
    `backward(*output_grads)`, both over NDArrays; calling the instance
    under `record()` tapes a node whose vjp runs your `backward`.

        class Sigmoid(autograd.Function):
            def forward(self, x):
                y = 1 / (1 + (-x).exp())
                self.save_for_backward(y)
                return y
            def backward(self, dy):
                (y,) = self.saved_tensors
                return dy * y * (1 - y)
    """

    def __init__(self):
        self.saved_tensors = ()

    def save_for_backward(self, *tensors):
        self.saved_tensors = tensors

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray

        with pause():
            outputs = self.forward(*inputs)
        ret_single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if ret_single else list(outputs)
        for o in outs:
            if not isinstance(o, NDArray):
                raise MXNetError("Function.forward must return NDArrays")

        if is_recording():
            entries = []
            tracked = False
            for x in inputs:
                ent = getattr(x, "_entry", None)
                if ent is not None:
                    entries.append(("node", ent[0], ent[1]))
                    tracked = True
                elif getattr(x, "_marked", False):
                    entries.append(("leaf", x))
                    tracked = True
                else:
                    entries.append(None)
            if tracked:
                ctx = outs[0].ctx
                n_in = len(inputs)

                def vjp_fn(cts):
                    ct_nd = [NDArray(c, ctx=ctx, _committed=True)
                             for c in cts]
                    with pause():
                        igrads = self.backward(*ct_nd)
                    if not isinstance(igrads, (list, tuple)):
                        igrads = [igrads]
                    if len(igrads) != n_in:
                        raise MXNetError(
                            "Function.backward returned %d grads for %d "
                            "inputs" % (len(igrads), n_in))
                    return tuple(g._data if isinstance(g, NDArray) else g
                                 for g in igrads)

                node = TapeNode(type(self).__name__, vjp_fn, entries,
                                [(o.shape, o._data.dtype) for o in outs])
                for i, o in enumerate(outs):
                    o._entry = (node, i)
        return outputs if not ret_single else outs[0]
