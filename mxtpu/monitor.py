"""Monitor — tensor-stat debugging attached to executors.

Reference: `python/mxnet/monitor.py` — Monitor(interval, stat_func)
installed on executors via `MXExecutorSetMonitorCallback`; every
`interval` batches `toc()` collects (name, stat) pairs for outputs
(and with monitor_all, inputs/params).

Here the executor exposes its arg/aux/output dicts directly, so the
monitor pulls stats instead of receiving callbacks — same API surface
(`install`, `tic`, `toc`, `toc_print`).

Every collected (name, stat) pair is also emitted into the telemetry
stream (`mxtpu/telemetry.py`, kind ``monitor``) carrying the CURRENT
training-step correlation id, so aux/weight stats line up with the
step/compile/kvstore records on the merged timeline.
"""
from __future__ import annotations

import logging
import re
from typing import Any, Callable, List, Optional, Tuple

from .ndarray.ndarray import NDArray

__all__ = ["Monitor"]


class Monitor(object):
    def __init__(self, interval: int, stat_func: Optional[Callable] = None,
                 pattern: str = ".*", sort: bool = False,
                 monitor_all: bool = False):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue: List[Tuple[int, str, Any]] = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all

    def install(self, exe):
        exe.set_monitor_callback(self.stat_func, self.monitor_all)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self) -> List[Tuple[int, str, Any]]:
        if not self.activated:
            return []
        for exe in self.exes:
            for arr in exe.outputs:
                arr.wait_to_read()
            named = list(zip(exe._symbol.list_outputs(), exe.outputs))
            if self.monitor_all:
                named += list(exe.arg_dict.items())
                named += list(exe.aux_dict.items())
            for name, arr in named:
                if self.re_prog.match(name) and isinstance(arr, NDArray):
                    self.queue.append((self.step, name,
                                       self.stat_func(arr)))
        self.activated = False
        res = []
        if self.sort:
            self.queue.sort(key=lambda x: x[1])
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    res.append((n, k, str(float(v.asscalar()))))
                else:
                    res.append((n, k, str(v.asnumpy())))
        self.queue = []
        if res:
            from . import telemetry as _tel

            step_id = _tel.current_step()
            for n, k, v in res:
                _tel.record("monitor", step=step_id, batch=int(n),
                            name=k, value=v)
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
