"""Custom operator API (reference: `python/mxnet/operator.py`, 1,101 LoC
CustomOp/CustomOpProp/register; C side `src/operator/custom/custom.cc`).

Define an op in python, use it from nd/sym/gluon — including inside
hybridized/compiled graphs (the forward/backward run as host callbacks
via `jax.pure_callback`; see `mxtpu/ops/custom_op.py`).

    @mx.operator.register("sigmoid2")
    class Sigmoid2Prop(mx.operator.CustomOpProp):
        def list_arguments(self): return ["data"]
        def list_outputs(self): return ["output"]
        def infer_shape(self, in_shape): return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes): return Sigmoid2()

    class Sigmoid2(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], 1/(1+(-in_data[0]).exp()))
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            y = out_data[0]
            self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))

    y = mx.nd.Custom(x, op_type="sigmoid2")
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

from .base import MXNetError
from .ndarray.ndarray import NDArray
from .ops.custom_op import PROP_REGISTRY

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators"]


class CustomOp(object):
    """User-defined operator body (reference `operator.py:CustomOp`)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    @staticmethod
    def assign(dst: NDArray, req: str, src):
        """Write `src` into `dst` honoring the write request (reference
        CustomOp.assign)."""
        if req in ("null", None):
            return
        if not isinstance(src, NDArray):
            from .ndarray.ndarray import array

            src = array(src)
        if req in ("write", "inplace"):
            src.copyto(dst)
        elif req == "add":
            dst._set_jax(dst._data + src._data)
        else:
            raise MXNetError("unknown req %r" % req)


class CustomOpProp(object):
    """Op metadata: arguments/outputs, shape/type inference, operator
    factory (reference `operator.py:CustomOpProp`)."""

    def __init__(self, need_top_grad: bool = True, **kwargs):
        self.need_top_grad_ = need_top_grad
        self._kwargs = kwargs

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape):
        """Default: all outputs shaped like the first input."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def infer_storage_type(self, in_stype):
        return in_stype, ["default"] * len(self.list_outputs()), []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError


def register(reg_name: str):
    """Class decorator registering a CustomOpProp under `op_type`
    (reference `operator.py:register` → MXCustomOpRegister)."""

    def deco(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        PROP_REGISTRY[reg_name] = prop_cls
        return prop_cls
    return deco


def get_all_registered_operators() -> List[str]:
    return list(PROP_REGISTRY)
