"""Training callbacks (reference: `python/mxnet/callback.py`).

`do_checkpoint` (:55) — epoch callback writing prefix-symbol.json +
params; `Speedometer` (:120) — periodic samples/sec + metric logging;
`log_train_metric`, `ProgressBar`, `LogValidationMetricsCallback`.
"""
from __future__ import annotations

import logging
import math
import time

from .model import save_checkpoint

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar", "LogValidationMetricsCallback"]


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Epoch callback checkpointing a Module (reference `callback.py:30`)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch callback writing `prefix-symbol.json` +
    `prefix-%04d.params` (reference `callback.py:55`)."""
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer(object):
    """Batch-end callback logging samples/sec (and, optionally, the
    running metric values) once every `frequent` batches.

    Behavioral spec per reference `python/mxnet/callback.py:120`: the
    rate covers the batches since the previous report, the metric is
    optionally reset after each report so values are per-window, and a
    batch counter that moved backwards (new epoch) restarts the timing
    window.  Implementation is window-accounted on a monotonic clock —
    it reports a correct rate even when the callback is invoked on a
    different cadence than `frequent` (e.g. resumed mid-epoch).
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = max(1, int(frequent))
        self.auto_reset = auto_reset
        self._window_start = None   # monotonic ts of window begin
        self._window_batches = 0    # batches accumulated in the window
        self._prev_nbatch = None

    def _restart_window(self):
        self._window_start = time.monotonic()
        self._window_batches = 0

    def __call__(self, param):
        nbatch = param.nbatch
        if self._window_start is None or self._prev_nbatch is None \
                or nbatch < self._prev_nbatch:
            # first call, or the batch counter wrapped (new epoch)
            self._prev_nbatch = nbatch
            self._restart_window()
            return
        self._window_batches += max(0, nbatch - self._prev_nbatch)
        self._prev_nbatch = nbatch
        if nbatch % self.frequent != 0 or self._window_batches == 0:
            return
        elapsed = time.monotonic() - self._window_start
        rate = (self._window_batches * self.batch_size / elapsed
                if elapsed > 0 else float("inf"))
        parts = ["Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec"
                 % (param.epoch, nbatch, rate)]
        if param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                parts.append("%s=%f" % (name, value))
            if self.auto_reset:
                param.eval_metric.reset()
        logging.info("\t".join(parts))
        self._restart_window()


class ProgressBar(object):
    """Text progress bar for each epoch (reference `callback.py:185`)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s\r", prog_bar, percents, "%")


class LogValidationMetricsCallback(object):
    def __call__(self, param):
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)
