"""mx.serve — continuous-batching model server over the compile-once stack.

The "millions of users" front end (ROADMAP open item 1): everything
below it already exists — shape-bucketed dispatch + AOT warmup
(`mxtpu/compile_cache.py`), typed OOM forensics (`mxtpu/health.py`),
resilience chokepoints (`mxtpu/resilience.py`), SLO telemetry
(`mxtpu/telemetry.py`) — and this module is what drives those pieces
under live traffic.  Four layers, smallest first:

  * **Request/future plumbing** — :meth:`Server.submit` enqueues a
    request (one or more rows of one model's input) and returns a
    future; :meth:`Server.infer` is the blocking convenience.

  * **Continuous micro-batcher** — one batcher thread per model pops
    the queue and packs ragged in-flight requests into the pow2 (or
    ``mult:N``/``fixed:...``) bucket set, dispatching ONE compiled
    program per batch.  New requests are admitted at every bucket
    boundary — the batcher never waits for a "full" batch; it lingers
    at most ``MXTPU_SERVE_BATCH_WAIT_US`` when the queue runs dry
    below the cap, so an idle server stays at ~one-request latency
    while a loaded server rides full buckets.  Every bucket size was
    AOT-warmed at :meth:`Server.add_model`, so the steady state
    compiles nothing.

  * **Admission control + graceful degradation** — per-(model, tenant)
    queued-row caps shed excess load with the typed
    :class:`~mxtpu.base.RequestShedError` (reason ``queue_full`` /
    ``draining`` / ``timeout``) instead of letting queues grow without
    bound; dispatch runs under the ``serve`` resilience chokepoint
    (fault injection + backoff retry), and a typed
    :class:`~mxtpu.base.MemoryExhaustedError` SHRINKS the model's
    bucket cap to the next smaller warmed bucket and requeues the
    batch rather than failing requests — shed, shrink, retry, never
    crash the serve loop (an OOM already at the smallest bucket fails
    typed: there is nothing left to shrink).

  * **Replica frontend + failover client** — :class:`HttpFrontend`
    serves a JSON-over-HTTP predict API per replica
    (``tools/launch.py --serve-replicas N`` spawns the fleet);
    :class:`Client` round-robins over replicas and FAILS OVER on
    connection errors, recording ``serve_failover::<replica>``
    counters + ``failover`` telemetry events so a SIGKILLed replica
    mid-load completes with zero failed requests and a named corpse
    (`tools/check_serving.py` is the chaos guard).

SLO surface: per-model request-latency histograms
(`telemetry.Histogram`, p50/p95/p99) plus queue-depth / in-flight /
batch-occupancy gauges, all visible in ``mx.telemetry.metrics()``
under ``"serve"`` and ``"histograms"`` — the same numbers
``benchmark/python/bench_serving.py`` reports throughput against.

See `docs/serving.md` for the architecture and the chaos workflow.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .base import (MemoryExhaustedError, MXNetError, RequestShedError,
                   getenv, getenv_int)
from . import compile_cache as _cc
from . import perf as _perf
from . import tracing as _tracing

__all__ = [
    "Server",
    "HttpFrontend",
    "Client",
    "serve_forever",
    "wait_ready",
]


def _max_batch_default() -> int:
    return max(1, getenv_int("MXTPU_SERVE_MAX_BATCH", 32))


def _queue_cap_default() -> int:
    return max(1, getenv_int("MXTPU_SERVE_QUEUE_CAP", 1024))


def _batch_wait_default() -> float:
    return max(0.0, getenv_int("MXTPU_SERVE_BATCH_WAIT_US", 2000) / 1e6)


def _timeout_default() -> float:
    val = getenv("MXTPU_SERVE_TIMEOUT", "30")
    return float(val or 30)


# every live Server in the process; the ONE "serve" metrics provider
# folds them all, so a second Server (a canary next to the production
# one) can neither silently replace the first in metrics() nor yank
# the survivor's gauges out of telemetry when it closes
_SERVERS: "weakref.WeakSet" = weakref.WeakSet()


def _fleet_metrics() -> Dict[str, Any]:
    servers = list(_SERVERS)
    if not servers:
        return {}
    if len(servers) == 1:
        return servers[0]._metrics()
    out: Dict[str, Any] = {"queue_depth": 0, "inflight": 0,
                           "batch_occupancy_pct": 0.0,
                           "draining": False, "models": {}}
    for s in servers:
        m = s._metrics()
        out["queue_depth"] += m["queue_depth"]
        out["inflight"] += m["inflight"]
        out["batch_occupancy_pct"] = max(out["batch_occupancy_pct"],
                                         m["batch_occupancy_pct"])
        out["draining"] = out["draining"] or m["draining"]
        out["models"].update(m["models"])
    return out


class _Future(object):
    """Result slot for one submitted request."""

    __slots__ = ("_ev", "_val", "_exc")

    def __init__(self):
        self._ev = threading.Event()
        self._val = None
        self._exc: Optional[BaseException] = None

    def _set_result(self, val) -> None:
        self._val = val
        self._ev.set()

    def _set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._ev.set()

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block for the output (np.ndarray, or a tuple for
        multi-output models).  Raises what the server raised — a
        :class:`RequestShedError` for shed requests."""
        if not self._ev.wait(timeout):
            raise TimeoutError("serve request still pending after %ss"
                               % timeout)
        if self._exc is not None:
            raise self._exc
        return self._val


class _Request(object):
    __slots__ = ("x", "n", "tenant", "future", "t_enq", "deadline",
                 "trace", "t_pop")

    def __init__(self, x: np.ndarray, tenant: str, deadline: float,
                 trace=None):
        self.x = x
        self.n = int(x.shape[0])
        self.tenant = tenant
        self.future = _Future()
        self.t_enq = time.monotonic()
        self.deadline = deadline
        # mx.tracing context from the frontend's traceparent header
        # (None when the caller is untraced); t_pop marks when the
        # batcher popped it — the queue_wait/batch_linger boundary
        self.trace = trace
        self.t_pop = 0.0


class _ModelEntry(object):
    """One hosted model: its predict callable, bucket policy, dynamic
    batch cap (OOM-shrinkable), queue, and latency histogram."""

    def __init__(self, name: str, predict: Callable[[np.ndarray], Any],
                 dtype: str, sample_shape: Optional[Tuple[int, ...]],
                 max_batch: int, bucket_spec: str, queue_cap: int):
        from . import telemetry as _tel

        self.name = name
        self.predict = predict
        self.dtype = np.dtype(dtype)
        self.sample_shape = tuple(sample_shape) if sample_shape else None
        # the warmed signature set; the EFFECTIVE cap is the largest
        # bucket <= the requested cap, so every dispatch pads to a
        # warmed bucket and steady state never compiles (a cap like 20
        # under pow2 would otherwise clamp 17-row batches to an
        # unwarmed (20, ...) signature)
        self.buckets = _cc.bucket_set(int(max_batch), bucket_spec)
        self.max_batch = self.buckets[-1]
        self.bucket_spec = bucket_spec
        self.queue_cap = int(queue_cap)
        self.queue: collections.deque = collections.deque()
        self.queued_rows = 0
        self.tenant_rows: Dict[str, int] = {}
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.inflight_rows = 0
        # full request latency (enqueue -> result), seconds
        self.hist = _tel.histogram("serve_latency_s::%s" % name)
        self.thread: Optional[threading.Thread] = None
        # the model's mx.inspect record (set by add_model when the
        # model exposes one) — the handle the mx.hbm capacity consults
        # use at add time and on the OOM shrink path
        self.hbm_rec = None


class Server(object):
    """In-process continuous-batching model server.

    ::

        srv = mx.serve.Server()
        srv.add_model("mlp", net, input_shape=(10,))   # AOT-warms buckets
        srv.start()
        out = srv.infer("mlp", np.random.rand(3, 10))  # (3, ...) rows

    Thread-safe: :meth:`submit` may be called from any number of
    frontend threads; each model has ONE batcher thread, so per-model
    dispatch is serialized (outputs are deterministic) while distinct
    models run concurrently.
    """

    def __init__(self, max_batch: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 batch_wait_s: Optional[float] = None,
                 request_timeout_s: Optional[float] = None,
                 bucket_spec: Optional[str] = None):
        self.max_batch = max_batch or _max_batch_default()
        self.queue_cap = queue_cap or _queue_cap_default()
        self.batch_wait_s = _batch_wait_default() \
            if batch_wait_s is None else float(batch_wait_s)
        # env-defaulted values may be re-resolved after an `mx.tune`
        # auto-apply in add_model; EXPLICIT constructor args win over
        # any tuned config
        self._batch_wait_explicit = batch_wait_s is not None
        self._max_batch_explicit = max_batch is not None
        self.request_timeout_s = _timeout_default() \
            if request_timeout_s is None else float(request_timeout_s)
        self.bucket_spec = bucket_spec or _cc.get_bucket_policy() or "pow2"
        _cc._parse_policy(self.bucket_spec)  # validate eagerly
        self._entries: Dict[str, _ModelEntry] = {}
        # RLock: the flight recorder's signal handler serializes
        # metrics() — which calls our provider — on whatever thread the
        # signal lands on; if that thread held this lock, a plain Lock
        # would deadlock the dump (same rationale as telemetry._lock)
        self._lock = threading.RLock()
        self._started = False
        self._draining = False
        self._stopped = False
        self._last_occupancy = 0.0
        from . import telemetry as _tel

        _SERVERS.add(self)
        _tel.register_metrics_provider("serve", _fleet_metrics)

    # -- model hosting -----------------------------------------------------

    def add_model(self, name: str, model: Any,
                  input_shape: Optional[Sequence[int]] = None,
                  dtype: str = "float32",
                  max_batch: Optional[int] = None,
                  warmup: bool = True) -> None:
        """Host ``model`` under ``name``.

        ``model`` is a hybridized gluon block (anything with
        ``warmup``/``__call__``) or a plain callable
        ``fn(np.ndarray[batch, ...]) -> np.ndarray`` (batch-major
        outputs).  ``input_shape`` is ONE sample's shape (no batch
        dim); with a block it enables AOT warmup of the full bucket
        set (:func:`compile_cache.bucket_set`), so the replica's
        steady state compiles nothing.  Call before :meth:`start` or
        while running (multi-tenant hosting adds models live)."""
        if self._stopped:
            raise MXNetError("server is stopped")
        # mx.tune: with MXTPU_TUNE=apply, a persisted serve config for
        # this model name installs its knobs (batch wait, bucket cap)
        # before the entry is built and warmed.  Explicit constructor
        # args always win over the tuned env defaults.
        from . import tune as _tune

        if _tune.apply_enabled():
            applied = _tune.maybe_apply(name=name,
                                        profile="serve",
                                        site="serve.add_model")
            if applied is not None:
                if not self._batch_wait_explicit:
                    self.batch_wait_s = _batch_wait_default()
                if not self._max_batch_explicit:
                    self.max_batch = _max_batch_default()
        cap = int(max_batch or self.max_batch)
        predict = self._as_predict(model, dtype)
        entry = _ModelEntry(name, predict, dtype,
                            input_shape, cap, self.bucket_spec,
                            self.queue_cap)
        buckets = entry.buckets  # effective cap = buckets[-1] <= cap
        if warmup and input_shape is not None and \
                hasattr(model, "warmup"):
            model.warmup([[(b,) + tuple(input_shape)] for b in buckets],
                         dtype=dtype)
        from . import profiler as _prof
        from . import telemetry as _tel

        # mx.hbm capacity consult: warmup just compiled (and analyzed)
        # the whole bucket ladder, so the per-program capacity model is
        # a dict fit away.  The prediction always lands in telemetry as
        # an advisory; ``MXTPU_HBM_PRESHRINK=1`` additionally trims the
        # cap to the largest bucket predicted to fit live headroom.
        # Best-effort by contract — this never fails add_model.
        try:
            rec = getattr(getattr(model, "_cached_op", None),
                          "_insp", None)
            if rec is not None:
                from . import hbm as _hbm

                entry.hbm_rec = rec
                fit = _hbm.max_batch(rec, kind="infer",
                                     buckets=list(buckets),
                                     analyze=False)
                if fit is not None:
                    _tel.record("serve", action="hbm_capacity",
                                model=name, fit_max_batch=fit,
                                headroom_bytes=_hbm.headroom())
                    if getenv_int("MXTPU_HBM_PRESHRINK", 0) and \
                            0 < fit < entry.max_batch:
                        entry.max_batch = fit
                        _prof.inc_stat("serve_hbm_preshrink")
        except Exception:
            pass

        with self._lock:
            if name in self._entries:
                raise MXNetError("model %r already hosted" % name)
            self._entries[name] = entry
            if self._started:
                self._start_entry(entry)
        _prof.inc_stat("serve_models")
        _tel.record("serve", action="add_model", model=name,
                    buckets=",".join(str(b) for b in buckets),
                    max_batch=entry.max_batch)

    @staticmethod
    def _as_predict(model: Any, dtype: str) -> Callable[[np.ndarray], Any]:
        if not callable(model):
            raise MXNetError("model must be callable, got %r"
                             % type(model))
        if not hasattr(model, "hybridize") and \
                not hasattr(model, "warmup"):
            return model  # plain fn(np) -> np
        from . import ndarray as _nd

        def predict(x: np.ndarray):
            out = model(_nd.array(x, dtype=dtype))
            if isinstance(out, (list, tuple)):
                return tuple(o.asnumpy() for o in out)
            return out.asnumpy()
        return predict

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Server":
        with self._lock:
            if self._started:
                return self
            self._started = True
            for entry in self._entries.values():
                self._start_entry(entry)
        return self

    def _start_entry(self, entry: _ModelEntry) -> None:
        t = threading.Thread(target=self._batcher_loop, args=(entry,),
                             name="mxserve-%s" % entry.name, daemon=True)
        entry.thread = t
        t.start()

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown (the SIGTERM path): stop admitting —
        further :meth:`submit` sheds with reason ``draining`` — finish
        everything already queued/in flight, then stop the batcher
        threads.  Returns True when fully drained within ``timeout``.
        Idempotent."""
        from . import telemetry as _tel

        with self._lock:
            first = not self._draining
            self._draining = True
            entries = list(self._entries.values())
        if first:
            _tel.record("serve", action="drain")
        deadline = time.monotonic() + max(0.0, timeout)
        ok = True
        for entry in entries:
            with entry.cond:
                entry.cond.notify_all()
            t = entry.thread
            if t is not None:
                t.join(max(0.0, deadline - time.monotonic()))
                ok = ok and not t.is_alive()
        self._stopped = True
        return ok

    def close(self) -> None:
        """Drain (briefly); the "serve" metrics provider stays
        registered until the LAST live server closes."""
        from . import telemetry as _tel

        self.drain(timeout=5.0)
        _SERVERS.discard(self)
        if not _SERVERS:
            _tel.unregister_metrics_provider("serve")

    @property
    def draining(self) -> bool:
        return self._draining

    # -- submission / admission control ------------------------------------

    def submit(self, model: str, x, tenant: str = "default",
               timeout: Optional[float] = None, trace=None) -> _Future:
        """Enqueue rows for ``model`` and return the future.  ``x`` is
        one sample (``sample_shape``) or a batch of rows (leading
        batch dim).  Admission control runs HERE, on the caller's
        thread: a full per-tenant queue or a draining server RAISES
        the typed :class:`RequestShedError` synchronously (immediate
        backpressure — the caller never holds a future for work that
        was never admitted); by the time work reaches the batcher it
        is admitted, and only a deadline expiring in-queue sheds
        asynchronously through the future."""
        from . import profiler as _prof

        entry = self._entries.get(model)
        if entry is None:
            raise MXNetError("unknown model %r (hosted: %s)"
                             % (model, self.models()))
        if not self._started:
            # admitting with no batcher thread would orphan the
            # future: it hangs until its timeout instead of shedding
            raise MXNetError("server not started — call start() (or "
                             "HttpFrontend.start()) before submit()")
        x = np.ascontiguousarray(x, dtype=entry.dtype)
        if entry.sample_shape is not None and \
                x.shape == entry.sample_shape:
            x = x[None]  # one bare sample -> a 1-row batch
        if x.ndim == 0 or x.shape[0] < 1:
            raise MXNetError("request needs at least one row")
        if entry.sample_shape is not None and \
                tuple(x.shape[1:]) != entry.sample_shape:
            raise MXNetError(
                "model %r expects sample shape %s, got rows of %s"
                % (model, entry.sample_shape, tuple(x.shape[1:])))
        budget = self.request_timeout_s if timeout is None else timeout
        req = _Request(x, tenant, time.monotonic() + budget,
                       trace=trace)
        with entry.cond:
            # checked UNDER the batcher's cond: the batcher exits its
            # loop holding this lock (queue empty + draining), so a
            # check outside it could append after the last pop — an
            # orphaned future that times out instead of shedding typed
            if self._draining or self._stopped:
                raise self._shed(entry, req, "draining", deliver=False)
            have = entry.tenant_rows.get(tenant, 0)
            if have + req.n > entry.queue_cap:
                raise self._shed(entry, req, "queue_full",
                                 deliver=False)
            entry.queue.append(req)
            entry.queued_rows += req.n
            entry.tenant_rows[tenant] = have + req.n
            entry.cond.notify()
        _prof.inc_stat("serve_submitted")
        return req.future

    def infer(self, model: str, x, tenant: str = "default",
              timeout: Optional[float] = None, trace=None):
        """Blocking :meth:`submit` — returns the output rows."""
        budget = self.request_timeout_s if timeout is None else timeout
        # result() gets slack over the queue deadline: an admitted
        # request that expires in-queue is shed by the BATCHER with
        # the typed error, which beats an opaque client TimeoutError
        return self.submit(model, x, tenant, timeout, trace=trace) \
            .result(budget + 5.0)

    def _shed(self, entry: _ModelEntry, req: _Request, reason: str,
              deliver: bool = True) -> RequestShedError:
        """Account one shed.  ``deliver=True`` fails the future (the
        batcher's in-queue timeout path); ``deliver=False`` returns
        the error for the submitter to raise synchronously."""
        from . import profiler as _prof
        from . import telemetry as _tel

        _prof.inc_stat("serve_shed")
        _prof.inc_stat("serve_shed::%s" % reason)
        _tel.record("serve", action="shed", model=entry.name,
                    tenant=req.tenant, reason=reason, rows=req.n)
        err = RequestShedError(
            "request (%d rows, tenant %r, model %r) shed: %s"
            % (req.n, req.tenant, entry.name, reason), reason=reason)
        if deliver:
            req.future._set_exception(err)
        return err

    # -- the micro-batcher -------------------------------------------------

    def _pop_admitted(self, entry: _ModelEntry,
                      fit: Optional[int] = None) -> Optional[_Request]:
        """Pop the queue head (caller holds entry.lock), shedding
        requests whose deadline expired while queued.  With ``fit``,
        a LIVE head wider than ``fit`` rows is left in place (it
        starts the NEXT bucket) and None is returned: the fit check
        must run AFTER expiry sheds — a caller-side check on a head
        that then gets shed would admit its unchecked successor and
        pack the batch past the cap (an unwarmed raw dispatch)."""
        while entry.queue:
            req = entry.queue[0]
            expired = time.monotonic() > req.deadline
            if not expired and fit is not None and req.n > fit:
                return None
            entry.queue.popleft()
            entry.queued_rows -= req.n
            entry.tenant_rows[req.tenant] = \
                entry.tenant_rows.get(req.tenant, 0) - req.n
            if expired:
                self._shed(entry, req, "timeout")
                continue
            req.t_pop = time.monotonic()
            return req
        return None

    def _batcher_loop(self, entry: _ModelEntry) -> None:
        """One thread per model.  CONTINUOUS batching: re-admit from
        the queue at every bucket boundary; linger at most
        ``batch_wait_s`` when below the cap with an empty queue."""
        while True:
            with entry.cond:
                while not entry.queue and not self._draining:
                    entry.cond.wait(0.1)
                if not entry.queue and self._draining:
                    return
                first = self._pop_admitted(entry)
            if first is None:
                continue
            batch = [first]
            rows = first.n
            deadline = time.monotonic() + self.batch_wait_s
            while rows < entry.max_batch:
                with entry.cond:
                    if not entry.queue:
                        if self._draining:
                            break
                        wait = deadline - time.monotonic()
                        if wait <= 0:
                            break
                        entry.cond.wait(wait)
                        if not entry.queue:
                            continue  # re-check deadline
                    if entry.queue[0].n + rows > entry.max_batch:
                        break  # head starts the NEXT bucket
                    nxt = self._pop_admitted(
                        entry, fit=entry.max_batch - rows)
                if nxt is not None:
                    batch.append(nxt)
                    rows += nxt.n
                # nxt None: expiry sheds emptied the queue (loop waits)
                # or exposed a head too wide for the remaining fit
                # (the head-fits check above breaks next iteration)
            self._dispatch(entry, batch, rows)

    def _dispatch(self, entry: _ModelEntry, batch: List[_Request],
                  rows: int) -> None:
        """Pack -> pad to bucket -> ONE compiled program -> slice.
        Never raises: errors land in the request futures, OOM shrinks
        the bucket cap and requeues."""
        from . import profiler as _prof
        from . import resilience as _res
        from . import telemetry as _tel

        xs = batch[0].x if len(batch) == 1 else \
            np.concatenate([r.x for r in batch], axis=0)
        bucket = _cc.bucket_batch(rows, entry.bucket_spec)
        if bucket > entry.max_batch:
            # only reachable for a single overwide request (the
            # batcher never packs past the cap, and the cap is itself
            # a warmed bucket, so bucket_batch(rows<=cap) <= cap):
            # dispatch it raw at its own width
            bucket = entry.max_batch
        if bucket > rows:
            pad = np.zeros((bucket - rows,) + xs.shape[1:],
                           dtype=xs.dtype)
            xs = np.concatenate([xs, pad], axis=0)
        with entry.lock:
            entry.inflight_rows = rows
        _prof.set_stat("serve_inflight", self._inflight_rows())
        # phase attribution for the batcher: predict() is synchronous
        # (numpy out), so host_dispatch here IS the full dispatch wall;
        # the per-program device split comes from the CachedOp hook
        # underneath
        pt0 = _perf.begin()
        t_disp = time.monotonic()
        try:
            out = _res.guarded("serve", entry.predict, xs)
        except (MemoryExhaustedError, MemoryError) as e:
            self._degrade(entry, batch, bucket, e)
            return
        except BaseException as e:
            _prof.inc_stat("serve_errors")
            _tel.record("serve", action="error", model=entry.name,
                        error=type(e).__name__, detail=str(e)[:200])
            for req in batch:
                req.future._set_exception(e)
            return
        finally:
            with entry.lock:
                entry.inflight_rows = 0
            _prof.set_stat("serve_inflight", self._inflight_rows())
        _perf.end("serve:%s" % entry.name, "serve", pt0)
        self._fulfill(entry, batch, rows, bucket, out, t_disp)

    def _fulfill(self, entry: _ModelEntry, batch: List[_Request],
                 rows: int, bucket: int, out: Any,
                 t_disp: float = 0.0) -> None:
        from . import profiler as _prof

        outs = out if isinstance(out, tuple) else (out,)
        for o in outs:
            lead = getattr(o, "shape", (None,))[0]
            if lead not in (rows, bucket):
                err = MXNetError(
                    "model %r output leading dim %r is neither the "
                    "packed rows (%d) nor the bucket (%d) — serve "
                    "needs batch-major outputs" % (entry.name, lead,
                                                   rows, bucket))
                for req in batch:
                    req.future._set_exception(err)
                _prof.inc_stat("serve_errors")
                return
        now = time.monotonic()
        off = 0
        for req in batch:
            sliced = tuple(o[off:off + req.n] for o in outs)
            req.future._set_result(
                sliced if isinstance(out, tuple) else sliced[0])
            off += req.n
            lat = now - req.t_enq
            entry.hist.record(lat)
            # mx.tracing: the replica-side span tree — head-sampled,
            # or RETRO-kept when the request beat this model's rolling
            # p95 (the slow tail is always attributable); the segments
            # end at their true instants via `ago`
            if req.trace is not None and (
                    req.trace.sampled or _tracing.slow_keep(
                        "serve_latency_s::%s" % entry.name,
                        entry.hist, lat)):
                _tracing.note_exemplar(
                    "serve_latency_s::%s" % entry.name,
                    req.trace.trace_id, lat)
                t_pop = req.t_pop or now
                _tracing.record_span(
                    req.trace, "queue_wait",
                    max(0.0, t_pop - req.t_enq), ago=now - t_pop,
                    model=entry.name)
                if t_disp:
                    _tracing.record_span(
                        req.trace, "batch_linger",
                        max(0.0, t_disp - t_pop), ago=now - t_disp,
                        model=entry.name)
                    _tracing.record_span(
                        req.trace, "device", max(0.0, now - t_disp),
                        model=entry.name, rows=rows, bucket=bucket)
        # an overwide single request dispatches raw (rows > bucket):
        # its effective width is rows, not the cap — never report >100%
        occupancy = 100.0 * rows / max(1, bucket, rows)
        self._last_occupancy = occupancy
        _prof.inc_stat("serve_batches")
        _prof.inc_stat("serve_rows", rows)
        _prof.inc_stat("serve_requests", len(batch))
        _prof.set_stat("serve_batch_occupancy_pct", int(occupancy))
        _prof.set_stat("serve_queue_depth", self._queue_depth())
        _prof.set_stat("serve_max_batch", entry.max_batch)

    def _degrade(self, entry: _ModelEntry, batch: List[_Request],
                 bucket: int, exc: BaseException) -> None:
        """The OOM path: shrink the model's bucket cap to the next
        smaller WARMED bucket (the NEXT dispatch packs/pads smaller —
        and compiles nothing), requeue the batch at the front, and
        keep serving.  A request wider than the shrunken cap — or an
        OOM already at the smallest bucket, where no shrink exists —
        fails with the original typed error: requeueing it would just
        redispatch the same doomed batch in a busy loop until its
        queue deadline shed it as an opaque ``timeout``."""
        from . import profiler as _prof
        from . import telemetry as _tel

        smaller = [b for b in entry.buckets if b < bucket]
        target = smaller[-1] if smaller else 0
        # mx.hbm consult: when the census can predict what actually
        # fits the live headroom, jump straight to that bucket instead
        # of stepping one rung and OOMing again on the next dispatch.
        # Reactive path: analyze=False — never compiles here.
        if smaller and entry.hbm_rec is not None:
            try:
                from . import hbm as _hbm

                fit = _hbm.max_batch(entry.hbm_rec, kind="infer",
                                     buckets=smaller, analyze=False)
                if fit is not None and 0 < fit < target:
                    target = fit
            except Exception:
                pass
        with entry.cond:
            if smaller:
                entry.max_batch = min(entry.max_batch, target)
            requeue = []
            for req in batch:
                if not smaller or req.n > entry.max_batch:
                    req.future._set_exception(exc)
                    _prof.inc_stat("serve_oom_failed")
                else:
                    requeue.append(req)
            for req in reversed(requeue):
                entry.queue.appendleft(req)
                entry.queued_rows += req.n
                entry.tenant_rows[req.tenant] = \
                    entry.tenant_rows.get(req.tenant, 0) + req.n
            entry.cond.notify()
        if smaller:
            _prof.inc_stat("serve_oom_shrink")
            _tel.record("serve", action="oom_shrink", model=entry.name,
                        bucket=bucket, new_max_batch=entry.max_batch,
                        error=type(exc).__name__)
        else:
            # no shrink happened — counting this as one would read as
            # graceful degradation in the rollups when every request
            # in the batch in fact failed
            _tel.record("serve", action="oom_floor", model=entry.name,
                        bucket=bucket, error=type(exc).__name__)

    # -- observability -----------------------------------------------------

    def _queue_depth(self) -> int:
        with self._lock:
            entries = list(self._entries.values())
        return sum(e.queued_rows for e in entries)

    def _inflight_rows(self) -> int:
        with self._lock:
            entries = list(self._entries.values())
        return sum(e.inflight_rows for e in entries)

    def _metrics(self) -> Dict[str, Any]:
        """The ``metrics()["serve"]`` block (registered provider)."""
        with self._lock:
            entries = dict(self._entries)
        per_model = {}
        for name, e in entries.items():
            snap = e.hist.snapshot()
            per_model[name] = {
                "queued_rows": e.queued_rows,
                "inflight_rows": e.inflight_rows,
                "max_batch": e.max_batch,
                "latency_p50_s": snap["p50"],
                "latency_p95_s": snap["p95"],
                "latency_p99_s": snap["p99"],
                "requests": snap["count"],
            }
        return {
            "queue_depth": sum(e.queued_rows for e in entries.values()),
            "inflight": sum(e.inflight_rows for e in entries.values()),
            "batch_occupancy_pct": self._last_occupancy,
            "draining": self._draining,
            "models": per_model,
        }


# ---------------------------------------------------------------------------
# HTTP replica frontend
# ---------------------------------------------------------------------------

class HttpFrontend(object):
    """JSON-over-HTTP frontend for one :class:`Server` replica.

    Endpoints::

        POST /v1/<model>:predict   {"data": [[...]], "tenant": "t"}
          -> 200 {"output": [...], "replica": <rank>, "rows": n}
          -> 503 {"error": ..., "shed": true, "reason": ...}   (shed)
          -> 404 unknown model, 400 bad payload, 500 model error
        GET  /metrics   -> mx.telemetry.metrics() as JSON, or —
          content-negotiated via the Accept header
          (``application/openmetrics-text`` / ``text/plain``, what a
          Prometheus scraper sends) — the `mx.obs` OpenMetrics text
          exposition, so ONE scrape config covers serve replicas and
          training roles identically
        GET  /healthz   -> {"ok": true, "replica": <rank>, "models": [...]}

    ``port=0`` binds an ephemeral port (read it back from ``.port``).
    A threading HTTP server: one OS thread per in-flight request, all
    funneling into the server's per-model batcher — exactly the
    many-frontends-one-batcher shape the CachedOp thread-safety test
    covers.
    """

    def __init__(self, server: Server, port: Optional[int] = None,
                 host: str = "127.0.0.1"):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        self.server = server
        self.rank = getenv_int("MXTPU_SERVE_RANK", 0)
        if port is None:
            port = getenv_int("MXTPU_SERVE_PORT", 8080)
        srv = self.server
        rank = self.rank

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, code: int, payload: Dict[str, Any]):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from . import obs as _obs
                from . import telemetry as _tel

                if self.path == "/healthz":
                    self._reply(200, {"ok": not srv.draining,
                                      "replica": rank,
                                      "models": srv.models()})
                elif self.path == "/metrics":
                    # content negotiation: a Prometheus scraper asks
                    # for openmetrics-text/text-plain and gets the
                    # mx.obs exposition (same families as every
                    # training role's endpoint); the JSON default
                    # keeps the existing dashboards parsing
                    accept = self.headers.get("Accept", "") or ""
                    if "openmetrics" in accept or "text/plain" in accept:
                        body = _obs.openmetrics().encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         _obs.CONTENT_TYPE)
                        self.send_header("Content-Length",
                                         str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._reply(200,
                                    _tel._json_safe(_tel.metrics()))
                else:
                    self._reply(404, {"error": "no such path"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    model = req.get("model")
                    if self.path.startswith("/v1/") and \
                            self.path.endswith(":predict"):
                        model = self.path[len("/v1/"):-len(":predict")]
                    if not model or model not in srv.models():
                        self._reply(404, {"error": "unknown model %r"
                                          % model})
                        return
                    data = np.asarray(req["data"])
                except Exception as e:
                    self._reply(400, {"error": "bad request: %s" % e})
                    return
                # mx.tracing: continue the caller's trace (W3C
                # traceparent header) through the batcher; malformed
                # or absent headers mean an untraced request
                trc = _tracing.parse(self.headers.get("traceparent"))
                try:
                    out = srv.infer(model, data,
                                    tenant=req.get("tenant", "default"),
                                    trace=trc)
                except RequestShedError as e:
                    self._reply(503, {"error": str(e), "shed": True,
                                      "reason": e.reason,
                                      "replica": rank})
                    return
                except Exception as e:
                    self._reply(500, {"error": "%s: %s"
                                      % (type(e).__name__, e)})
                    return
                outs = out if isinstance(out, tuple) else (out,)
                reply = {
                    "output": outs[0].tolist() if len(outs) == 1
                    else [o.tolist() for o in outs],
                    "replica": rank, "rows": int(outs[0].shape[0])}
                if trc is not None:
                    reply["trace"] = trc.trace_id
                self._reply(200, reply)

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpFrontend":
        self.server.start()
        t = threading.Thread(target=self._httpd.serve_forever,
                             name="mxserve-http", daemon=True)
        self._thread = t
        t.start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)


def serve_forever(build_models: Callable[[Server], None],
                  port: Optional[int] = None,
                  ready_file: Optional[str] = None) -> None:
    """Run ONE replica until SIGTERM, then drain and exit — the body a
    ``launch.py --serve-replicas N`` child runs.

    ``build_models(server)`` registers (and warms) the replica's
    models; the replica then serves HTTP on ``port`` (default
    ``MXTPU_SERVE_PORT``).  Identity: role ``serve``, rank
    ``MXTPU_SERVE_RANK`` — telemetry snapshots/flight records merge
    per replica.  SIGTERM stops admission (sheds with ``draining``),
    finishes queued work, flushes telemetry, exits 0; SIGKILL is the
    chaos case — the CLIENT's failover keeps the fleet's zero-failed
    contract (`tools/check_serving.py`)."""
    import signal

    from . import resilience as _res
    from . import telemetry as _tel

    rank = getenv_int("MXTPU_SERVE_RANK", 0)
    _tel.set_identity(role="serve", rank=rank)
    _tel.install_flight_recorder()
    from . import obs as _obs

    _obs.ensure_started()  # the replica's own OpenMetrics endpoint +
    # sampler (queue depth / occupancy / SLO time series), next to the
    # frontend's content-negotiated /metrics
    server = Server()
    build_models(server)
    front = HttpFrontend(server, port=port).start()
    done = threading.Event()
    # forward=False: SIGTERM means DRAIN, not die — the previous
    # disposition (flight dump + terminate) must not run, the replica
    # finishes admitted work and exits 0 below
    _res.install_preemption_hook(done.set, forward=False)
    signal.signal(signal.SIGINT, lambda *a: done.set())
    if ready_file:
        with open(ready_file, "w") as f:
            f.write(str(front.port))
    done.wait()
    server.drain()
    front.close()
    _tel.flush()


# ---------------------------------------------------------------------------
# Failover client
# ---------------------------------------------------------------------------

class Client(object):
    """Closed-loop HTTP client over a replica fleet with failover.

    Sticky round-robin: requests go to the current replica until it
    FAILS (connection refused/reset/timeout, a response torn mid-body
    by a dying replica, or a 5xx that is not a shed), then the client
    moves on to the next replica and REPLAYS
    the request — inference is pure, so replay is safe, and a SIGKILLed
    replica mid-request costs one retry, not one failed request.  Each
    failover ticks ``serve_failover::serve<rank>`` (naming the replica
    given up on) and records a ``failover`` telemetry event, which is
    how the chaos guard's telemetry rollup names the dead replica.

    A 503 shed is NOT a failover: the replica is alive and protecting
    its SLO — the typed :class:`RequestShedError` propagates so the
    caller can back off.
    """

    def __init__(self, endpoints: Sequence[str],
                 timeout: float = 30.0, rounds: int = 3):
        if not endpoints:
            raise MXNetError("need at least one endpoint")
        self.endpoints = ["http://" + e if "://" not in e else e
                          for e in endpoints]
        self.timeout = float(timeout)
        self.rounds = max(1, int(rounds))
        self._cur = 0
        self._lock = threading.Lock()

    def _post(self, url: str, payload: Dict[str, Any],
              trace=None) -> Dict[str, Any]:
        import urllib.request

        headers = {"Content-Type": "application/json"}
        if trace is not None:
            headers["traceparent"] = trace.traceparent()
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(), headers=headers)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read())

    def predict(self, model: str, x, tenant: str = "default"):
        """POST one request, failing over across replicas.  Returns
        the output rows as np.ndarray.  Raises
        :class:`RequestShedError` on a shed, ``ConnectionError`` only
        after every replica failed ``rounds`` times."""
        import http.client
        import urllib.error

        from . import profiler as _prof
        from . import telemetry as _tel

        payload = {"data": np.asarray(x).tolist(), "tenant": tenant}
        # mx.tracing: ONE context for the whole call — a failover
        # replay stamps the ORIGINAL trace id, so one user request is
        # one trace fleet-wide no matter how many replicas it crossed
        trc = _tracing.start_request()
        t_req = time.monotonic()
        with self._lock:
            start = self._cur
        n = len(self.endpoints)
        last_err: Optional[Exception] = None
        for attempt in range(self.rounds * n):
            idx = (start + attempt) % n
            url = "%s/v1/%s:predict" % (self.endpoints[idx], model)
            try:
                out = self._post(url, payload, trace=trc)
                with self._lock:
                    self._cur = idx  # stickiness: stay on a live one
                _tracing.finish_request(
                    trc, time.monotonic() - t_req, name="client",
                    model=model, replica=out.get("replica"))
                return np.asarray(out["output"])
            except urllib.error.HTTPError as e:
                detail = {}
                try:
                    detail = json.loads(e.read())
                except Exception:
                    pass
                if e.code == 503 and detail.get("shed"):
                    raise RequestShedError(
                        detail.get("error", "shed"),
                        reason=detail.get("reason", "overload"))
                if e.code < 500:
                    # deterministic client error (404 unknown model,
                    # 400 bad payload): every replica would answer the
                    # same — surface it, don't burn rounds of replays
                    # or tick failover counters against live replicas
                    raise
                last_err = e
            except (urllib.error.URLError, ConnectionError, OSError,
                    TimeoutError,
                    # a SIGKILL mid-response tears the body after the
                    # headers: http.client raises IncompleteRead (an
                    # HTTPException, NOT an OSError) — replay it too
                    http.client.HTTPException) as e:
                last_err = e
            # this replica failed us: name it and move on (the trace
            # id on the event ties the failover to the SAME trace the
            # replay continues)
            _prof.inc_stat("serve_failover::serve%d" % idx)
            _tel.record("failover", site="serve",
                        replica="serve%d" % idx,
                        to="serve%d" % ((idx + 1) % n),
                        error=type(last_err).__name__,
                        trace=trc.trace_id if trc is not None
                        else None)
            if attempt + 1 >= n:  # every replica seen at least once:
                time.sleep(0.05 * (attempt // n + 1))  # back off a bit
        raise ConnectionError(
            "all %d replica(s) failed %d rounds (last: %s)"
            % (n, self.rounds, last_err))


def wait_ready(endpoints: Sequence[str], timeout: float = 60.0,
               expect_models: Sequence[str] = ()) -> bool:
    """Poll every replica's ``/healthz`` until all are up (and host
    ``expect_models``) or ``timeout`` passes."""
    import urllib.request

    eps = ["http://" + e if "://" not in e else e for e in endpoints]
    deadline = time.monotonic() + timeout
    pending = set(eps)
    while pending and time.monotonic() < deadline:
        for ep in sorted(pending):
            try:
                with urllib.request.urlopen(ep + "/healthz",
                                            timeout=2) as r:
                    h = json.loads(r.read())
                if h.get("ok") and set(expect_models) <= \
                        set(h.get("models", [])):
                    pending.discard(ep)
            except Exception:
                pass
        if pending:
            time.sleep(0.1)
    return not pending
