"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Matches the reference's headline number (BASELINE.md: ResNet-50
training, bs=32, fp32 — 298.51 img/s on 1xV100,
`docs/faq/perf.md:208-217`, measured via the Module path of
`example/image-classification/train_imagenet.py` with synthetic data).

Same methodology here: the gluon model-zoo ResNet-50 is traced to a
Symbol, bound through Module/GraphExecutor — forward+backward compile to
ONE fused XLA module, the optimizer applies as ONE fused whole-tree
update — and timed over synthetic data.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: MXTPU_BENCH_BATCH/WARMUP/ITERS (fp32 throughout — the
apples-to-apples comparison against the fp32 baseline).
"""
import json
import os
import time

BASELINE_TRAIN_IMGS_PER_SEC = 298.51  # 1xV100 fp32 bs=32
BATCH = int(os.environ.get("MXTPU_BENCH_BATCH", "32"))
WARMUP = int(os.environ.get("MXTPU_BENCH_WARMUP", "3"))
ITERS = int(os.environ.get("MXTPU_BENCH_ITERS", "20"))


def main():
    import numpy as np

    import mxtpu as mx
    from mxtpu import sym
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.io.io import DataBatch

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()

    # trace the gluon ResNet-50 into a Symbol, add the softmax head
    net = vision.resnet50_v1(classes=1000)
    net.initialize(ctx=ctx)
    x_trace = mx.nd.zeros((BATCH, 3, 224, 224), ctx=ctx)
    out_sym, _, _ = net._trace_symbol(x_trace)
    softmax = sym.SoftmaxOutput(data=out_sym,
                                label=sym.Variable("softmax_label"),
                                name="softmax")

    mod = mx.mod.Module(softmax, data_names=("data0",),
                        label_names=("softmax_label",), context=ctx)
    mod.bind(data_shapes=[("data0", (BATCH, 3, 224, 224))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})

    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.rand(BATCH, 3, 224, 224).astype("float32"),
                       ctx=ctx)
    label = mx.nd.array(rng.randint(0, 1000, (BATCH,)).astype("float32"),
                        ctx=ctx)
    batch = DataBatch(data=[data], label=[label])

    def step():
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()

    for _ in range(WARMUP):
        step()
    mx.nd.waitall()

    t0 = time.perf_counter()
    for _ in range(ITERS):
        step()
    mx.nd.waitall()
    dt = time.perf_counter() - t0

    imgs_per_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_bs%d" % BATCH,
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_TRAIN_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
