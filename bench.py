"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Matches the reference's headline number (BASELINE.md: ResNet-50
training, bs=32, fp32 — 298.51 img/s on 1xV100,
`docs/faq/perf.md:208-217`; measured by
`example/image-classification/train_imagenet.py` with synthetic data).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

BASELINE_TRAIN_IMGS_PER_SEC = 298.51  # 1xV100 fp32 bs=32
BATCH = 32
WARMUP = 3
ITERS = 20


def main():
    import numpy as np

    import mxtpu as mx
    from mxtpu import autograd
    from mxtpu.gluon import Trainer
    from mxtpu.gluon.loss import SoftmaxCrossEntropyLoss
    from mxtpu.gluon.model_zoo import vision

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = vision.resnet50_v1(classes=1000)
    net.initialize(ctx=ctx)
    net.hybridize()

    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.rand(BATCH, 3, 224, 224).astype("float32"),
                       ctx=ctx)
    label = mx.nd.array(rng.randint(0, 1000, (BATCH,)).astype("float32"),
                        ctx=ctx)
    loss_fn = SoftmaxCrossEntropyLoss()
    trainer = Trainer(net.collect_params(), "sgd",
                      {"learning_rate": 0.01, "momentum": 0.9})

    def step():
        with autograd.record():
            out = net(data)
            loss = loss_fn(out, label)
        loss.backward()
        trainer.step(BATCH)
        return loss

    for _ in range(WARMUP):
        step().wait_to_read()

    t0 = time.perf_counter()
    for _ in range(ITERS):
        loss = step()
    loss.wait_to_read()
    dt = time.perf_counter() - t0

    imgs_per_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_bs32",
        "value": round(imgs_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(imgs_per_sec / BASELINE_TRAIN_IMGS_PER_SEC,
                             4),
    }))


if __name__ == "__main__":
    main()
