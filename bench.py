"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Headline metric matches the reference's number (BASELINE.md: ResNet-50
training, bs=32, fp32 — 298.51 img/s on 1xV100, `docs/faq/perf.md:208-217`,
measured via the Module path of
`example/image-classification/train_imagenet.py` with synthetic data).

Methodology here: the gluon model-zoo ResNet-50 is traced to a Symbol,
bound through Module/GraphExecutor, and trained through
`mxtpu.FusedTrainLoop` — forward + backward + optimizer for K
consecutive steps compile to ONE donated XLA program (`lax.scan` over
the staged batches).  That is the framework's production train loop
(equivalence-tested against the per-step path in
`tests/test_fused_train.py`); it matters doubly on a remote-tunnel PJRT
client, where per-step dispatch latency (~tens of ms) otherwise
dominates.  Reported throughput is SUSTAINED (total images / total
wall-time over all timed windows), with per-window spread in `extra`
(VERDICT r2 weak #9: best-of-N masked a regression).

Additional configs ride in the same JSON line (driver contract is ONE
line):
  * bf16 (AMP compute policy, fp32 master weights) at bs=32 and bs=128 —
    the TPU-native analog of the reference's fp16 rows
    (`docs/faq/perf.md:166-176`);
  * MFU estimate (12.3 GFLOP/img training cost, reference-standard
    ResNet-50 fwd ~4.1 GFLOP x3) against MXTPU_PEAK_TFLOPS;
  * the legacy per-step-dispatch fp32 number, so the dispatch-overhead
    win of the fused loop stays visible.

Env knobs: MXTPU_BENCH_BATCH/WARMUP/ITERS/WINDOWS/SPP/SKIP_EXTRA/NET,
MXTPU_PEAK_TFLOPS.
"""
import json
import os
import subprocess
import sys
import time

BASELINE_TRAIN_IMGS_PER_SEC = 298.51     # 1xV100 fp32 bs=32 (training)
_START = time.time()
# skip remaining extra configs once this much wall time is spent — the
# driver kills long benches; a partial JSON line beats rc=143
BUDGET_S = float(os.environ.get("MXTPU_BENCH_BUDGET_S", "1500"))
TPU_WAIT_S = float(os.environ.get("MXTPU_BENCH_TPU_WAIT", "900"))


def _probe_tpu(timeout=150):
    """Try one tiny op on the accelerator in a SUBPROCESS — a wedged
    tunnel hangs forever in-process, a subprocess can be timed out.
    Returns 'ok', 'no_tpu' (no accelerator platform at all — fails in
    seconds), or 'wedged' (hung until the timeout)."""
    code = ("import jax, sys\n"
            "ds = jax.devices()\n"
            "if all(d.platform == 'cpu' for d in ds):\n"
            "    sys.exit(3)\n"
            "import jax.numpy as jnp\n"
            "jnp.ones((8, 8)).sum().block_until_ready()\n"
            "print('ok')\n")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout)
        if r.returncode == 0 and "ok" in r.stdout:
            return "ok"
        if r.returncode == 3:
            return "no_tpu"
        return "wedged"
    except subprocess.TimeoutExpired:
        return "wedged"


def wait_for_tpu():
    """Retry the probe until the tunnel answers or TPU_WAIT_S elapses
    (the round-3 bench died to a transient outage; don't repeat that).
    A host with NO accelerator platform bails immediately — only a
    wedged/flapping tunnel is worth waiting out.  Returns True when the
    accelerator is usable."""
    deadline = _START + TPU_WAIT_S
    attempt = 0
    while True:
        state = _probe_tpu()
        if state == "ok":
            return True
        if state == "no_tpu":
            return False
        attempt += 1
        if time.time() > deadline:
            return False
        print("# TPU probe %d failed (%s); retrying (%.0fs left)"
              % (attempt, state, deadline - time.time()), file=sys.stderr)
        time.sleep(min(60, max(5, deadline - time.time())))


def _budget_left():
    return BUDGET_S - (time.time() - _START)
BATCH = int(os.environ.get("MXTPU_BENCH_BATCH", "32"))
WARMUP = int(os.environ.get("MXTPU_BENCH_WARMUP", "2"))
ITERS = int(os.environ.get("MXTPU_BENCH_ITERS", "8"))
WINDOWS = int(os.environ.get("MXTPU_BENCH_WINDOWS", "3"))
SPP = int(os.environ.get("MXTPU_BENCH_SPP", "16"))  # steps per program
# 16 (r5, measured): bf16 bs128 2667 img/s vs 2614 at spp=8 — the
# ~33 ms/program tunnel dispatch gap amortizes further with no
# downside; staging cost per program doubles but the bench loop
# reuses a pre-staged stack (see run_config docstring)
SKIP_EXTRA = os.environ.get("MXTPU_BENCH_SKIP_EXTRA", "0") == "1"
# model-zoo net for the train bench; the recorded metric name follows,
# so non-default nets are self-describing (the degraded-path CPU test
# uses resnet18_v1 to keep its compile inside the tier-1 wall budget)
NET = os.environ.get("MXTPU_BENCH_NET", "resnet50_v1")
PEAK_TFLOPS = float(os.environ.get("MXTPU_PEAK_TFLOPS", "197"))
TRAIN_GFLOP_PER_IMG = 12.3


def _build_module(batch, dtype):
    import mxtpu as mx
    from mxtpu import sym
    from mxtpu.gluon.model_zoo import vision

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    with mx.amp.scope(dtype if dtype != "float32" else None):
        net = getattr(vision, NET)(classes=1000)
        net.initialize(ctx=ctx)
        x_trace = mx.nd.zeros((batch, 3, 224, 224), ctx=ctx)
        out_sym, _, _ = net._trace_symbol(x_trace)
        softmax = sym.SoftmaxOutput(data=out_sym,
                                    label=sym.Variable("softmax_label"),
                                    name="softmax")
        mod = mx.mod.Module(softmax, data_names=("data0",),
                            label_names=("softmax_label",), context=ctx)
        mod.bind(data_shapes=[("data0", (batch, 3, 224, 224))],
                 label_shapes=[("softmax_label", (batch,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})
    return mx, mod, ctx


def _synthetic_batch(mx, ctx, batch, seed=0, host=False):
    """host=True returns raw numpy payloads (for timing the
    host->device staging path); default wraps on-device."""
    import numpy as np

    from mxtpu.io.io import DataBatch

    rng = np.random.RandomState(seed)
    data_np = rng.rand(batch, 3, 224, 224).astype("float32")
    label_np = rng.randint(0, 1000, (batch,)).astype("float32")
    if host:
        return DataBatch(data=[data_np], label=[label_np])
    return DataBatch(data=[mx.nd.array(data_np, ctx=ctx)],
                     label=[mx.nd.array(label_np, ctx=ctx)])


def run_config(batch, dtype, measure_stage=False):
    """Sustained fused-loop train throughput for one (batch, dtype)
    config; returns (images/sec, per-window images/sec list,
    stage_ms_per_program).  With measure_stage, one timed pass stacks
    HOST-resident (numpy) batches — the genuine host->device staging
    cost a real input pipeline must hide per K-step program (the
    throughput loop itself reuses a pre-staged stack; a device-side
    re-stack would only time an on-device concat)."""
    import jax

    mx, mod, ctx = _build_module(batch, dtype)
    loop = mx.FusedTrainLoop(mod, steps_per_program=SPP,
                             collect_outputs=False)
    # stage once; the (K, ...) data stack is NOT donated, so it is
    # reusable across programs — input-pipeline cost is measured by the
    # IO benchmarks, not here (reference uses synthetic data too)
    stack = loop.stack_batches(
        [_synthetic_batch(mx, ctx, batch, seed=k) for k in range(SPP)])
    jax.block_until_ready(stack)
    stage_ms = 0.0
    if measure_stage:
        host_batches = [_synthetic_batch(mx, ctx, batch, seed=k,
                                         host=True)
                        for k in range(SPP)]
        # min-of-3: a single remote-tunnel latency spike would skew the
        # attribution (same rationale as the multi-window throughput)
        trials = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(loop.stack_batches(host_batches))
            trials.append((time.perf_counter() - t0) * 1e3)
        stage_ms = min(trials)
        del host_batches

    for _ in range(WARMUP):
        loop.run_stacked(stack)
    mx.nd.waitall()

    windows = []
    total_t = 0.0
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loop.run_stacked(stack)
        mx.nd.waitall()
        dt = time.perf_counter() - t0
        total_t += dt
        windows.append(batch * SPP * ITERS / dt)
    sustained = batch * SPP * ITERS * WINDOWS / total_t
    return sustained, windows, stage_ms


def run_per_step_fp32(batch):
    """Legacy per-step dispatch path (forward/backward/update as separate
    device programs) — kept so the fused loop's dispatch win is visible.
    Multi-window like run_config: the tunnel's latency noise hits this
    path hardest, so a single window would be unrepresentative."""
    mx, mod, ctx = _build_module(batch, "float32")
    dbatch = _synthetic_batch(mx, ctx, batch)

    def step():
        mod.forward(dbatch, is_train=True)
        mod.backward()
        mod.update()

    for _ in range(WARMUP):
        step()
    mx.nd.waitall()
    n = max(ITERS * 2, 10)
    total_t = 0.0
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(n):
            step()
        mx.nd.waitall()
        total_t += time.perf_counter() - t0
    return batch * n * WINDOWS / total_t


def _mfu(ips):
    return round(ips * TRAIN_GFLOP_PER_IMG / (PEAK_TFLOPS * 1e3), 4)


def run_transformer(iters=12, warmup=1, B=8, T=1024, d_model=1024,
                    n_layers=8, d_ff=4096, vocab=8192):
    """Second flagship metric: sharded-TransformerLM training tokens/s
    on one chip (1-device mesh — collectives elide; the SAME
    make_train_step the multichip dryrun compiles at 8/16/32 devices).
    bf16, ZeRO-1-capable Adam path, flash attention via Pallas when the
    kernel compiles on this backend (falls back to the blocked jnp
    path otherwise).  The reference has no transformer; this row
    anchors the new-capability stack's single-chip performance.
    Returns (tokens_per_sec, est_mfu, used_pallas)."""
    import numpy as np
    import jax

    from mxtpu.parallel import transformer as tf
    from mxtpu.parallel.mesh import (create_mesh, AXIS_DP, AXIS_PP,
                                     AXIS_TP, AXIS_SP, AXIS_EP)

    mesh = create_mesh({AXIS_DP: 1, AXIS_PP: 1, AXIS_TP: 1,
                        AXIS_SP: 1, AXIS_EP: 1},
                       devices=jax.devices()[:1])
    used_pallas = False
    try:
        # probe the kernel in a REPRESENTATIVE context: inside
        # shard_map over the SAME mesh the train step uses, gradients
        # included (a bare-call probe can pass while the
        # manual-sharding trace path fails)
        from jax.sharding import PartitionSpec as P
        import jax.numpy as jnp

        from mxtpu.ops.pallas_attention import _use_pallas, \
            flash_attention

        if not _use_pallas():
            raise RuntimeError("no pallas backend")

        def probe(x):
            def loss(x):
                return flash_attention(x, x, x, causal=True) \
                    .astype(jnp.float32).sum()

            return jax.grad(loss)(x)

        x = jnp.ones((2, 128, 64), jnp.bfloat16)
        from mxtpu.parallel.mesh import get_shard_map
        sm = jax.jit(get_shard_map()(
            probe, mesh=mesh, in_specs=P(), out_specs=P()))
        jax.block_until_ready(sm(x))
        used_pallas = True
    except Exception:
        # kernel can't run here — flip the kill switch so the train
        # step's automatic routing takes the jnp attention path
        # instead of failing the same way and costing the whole row
        os.environ["MXTPU_NO_PALLAS"] = "1"

    # remat="dots": measured on chip (r5s3) 22% FASTER than saving all
    # activations at this size — the program is HBM-bound, so fewer
    # saved intermediates beats fewer recomputed FLOPs (120.6k vs
    # 98.7k tok/s; full remat lands between at 112k)
    cfg = tf.TransformerConfig(vocab=vocab, d_model=d_model, n_heads=8,
                               n_layers=n_layers, d_ff=d_ff, max_len=T,
                               dtype="bfloat16", remat="dots")
    params = tf.init_params(cfg, mesh, seed=0)
    opt = tf.init_opt_state(cfg, mesh)
    # fused K-step loop (make_fused_train_steps): ONE program per K
    # steps, the FusedTrainLoop principle applied to the transformer —
    # measured +6% over per-step dispatch on chip (127.9k vs 120.6k)
    K = 8
    step, sh = tf.make_fused_train_steps(cfg, mesh, K, lr=1e-3,
                                         optimizer="adam")
    rng = np.random.RandomState(0)
    toks = jax.device_put(rng.randint(0, cfg.vocab, (K, B, T))
                          .astype(np.int32), sh["data"])
    labs = jax.device_put(rng.randint(0, cfg.vocab, (K, B, T))
                          .astype(np.int32), sh["data"])
    # warmup counts fused programs now — ONE K-step program both
    # compiles and warms; two would burn 8 redundant steps of budget
    for _ in range(warmup):
        params, opt, losses = step(params, opt, toks, labs)
    loss = losses[-1]
    # SYNC BY VALUE, not by buffer readiness: with donate_argnums every
    # step output aliases a donated input, and (measured live, r5s3)
    # block_until_ready on such aliased buffers can return BEFORE the
    # execution finishes on the tunneled runtime — one bench run
    # reported a fantasy 64M tokens/s that way.  A value fetch is a
    # true data dependency; loss alone only pins the final forward
    # pass, so ALSO fetch a scalar derived from the UPDATED params,
    # which pins the last backward + optimizer update.  The two tiny
    # transfers are amortized over the window and keep the number
    # strictly conservative.
    import jax.numpy as jnp

    def _value_sync(params, loss):
        lv = float(loss)
        leaf = jax.tree_util.tree_leaves(params)[0]
        float(jnp.ravel(leaf)[0])      # depends on the applied update
        return lv

    # the warmup drain must sync the same way, BEFORE the budget check
    # — otherwise in-flight warmup work makes _budget_left() overstate
    # what remains and the clamp below turns too generous
    _value_sync(params, loss)
    # compile+warmup may have eaten the driver budget: shrink or bail
    # BEFORE the timed loop so the resnet JSON line always gets out
    # (the round-3 rc!=0-no-record failure mode).  The minimum unit is
    # now a whole K-step program, so the guard must cover one worst
    # case program (~30s/step), not one step
    if _budget_left() < 30 * K + 30:
        raise RuntimeError("budget exhausted after transformer warmup")
    # iters counts K-step fused programs (default iters=12, K=8 -> 2
    # programs = 16 steps; value-fetch round trip ~5% of the window)
    iters = max(1, min(max(1, iters // K) + 1,
                       int(_budget_left() // (30 * K))))
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt, losses = step(params, opt, toks, labs)
    lv = _value_sync(params, losses[-1])
    dt = time.perf_counter() - t0
    if not np.isfinite(lv):
        raise RuntimeError("transformer loss diverged: %r" % lv)
    tps = K * B * T * iters / dt
    # 6*N FLOP/token (fwd+bwd) + attention 12*L*d*T, causal-halved
    n_params = sum(int(np.prod(v.shape)) for v in params.values())
    flop_tok = 6.0 * n_params + 0.5 * 12.0 * cfg.n_layers \
        * cfg.d_model * T
    est_mfu = tps * flop_tok / (PEAK_TFLOPS * 1e12)
    return round(tps, 1), round(est_mfu, 4), used_pallas


def main():
    global SPP, ITERS, WINDOWS, WARMUP, BATCH
    tpu_ok = wait_for_tpu()
    extra = {"steps_per_program": SPP}
    if not tpu_ok:
        # the accelerator tunnel is down: report a degraded CPU run
        # rather than rc!=0 with no record (round-3 failure mode).
        # Tiny batch/steps: a CPU resnet50 compile+run at the real
        # config would blow the driver's wall budget
        import jax

        jax.config.update("jax_platforms", "cpu")
        SPP, ITERS, WINDOWS, WARMUP = 2, 1, 1, 1
        BATCH = min(BATCH, 8)
        extra["degraded"] = "tpu_unavailable_after_%ds_cpu_fallback" \
            % int(TPU_WAIT_S)
        extra["steps_per_program"] = SPP
    fp32, fp32_windows, fp32_stage_ms = run_config(
        BATCH, "float32", measure_stage=True)
    result = {
        "metric": "%s_train_imgs_per_sec_bs%d" % (NET.split("_v")[0], BATCH),
        "value": round(fp32, 2),
        "unit": "images/sec",
        "vs_baseline": round(fp32 / BASELINE_TRAIN_IMGS_PER_SEC, 3),
    }
    if not SKIP_EXTRA and tpu_ok:
        extra.update({
            "fp32_bs%d_mfu" % BATCH: _mfu(fp32),
            "fp32_bs%d_windows" % BATCH: [round(w, 1)
                                          for w in fp32_windows],
            # staging cost per K-step program vs its exec time: the
            # input-pipeline headroom number profile_train.py drills into
            "fp32_bs%d_stage_ms_per_program" % BATCH:
                round(fp32_stage_ms, 1),
            "fp32_bs%d_exec_ms_per_program" % BATCH:
                round(BATCH * SPP / max(fp32, 1e-9) * 1e3, 1),
        })
        configs = [(BATCH, "bfloat16")]
        if BATCH != 128:
            configs.append((128, "bfloat16"))
        for batch, dtype in configs:
            if _budget_left() < 240:
                extra["truncated_at"] = "bf16_bs%d" % batch
                break
            ips, wins, stage_ms = run_config(batch, dtype,
                                              measure_stage=True)
            extra["bf16_bs%d_imgs_per_sec" % batch] = round(ips, 2)
            extra["bf16_bs%d_mfu" % batch] = _mfu(ips)
            extra["bf16_bs%d_windows" % batch] = [round(w, 1)
                                                  for w in wins]
            extra["bf16_bs%d_stage_ms_per_program" % batch] = \
                round(stage_ms, 1)
        # layout A/B: channels-last conv internals (VERDICT r2 ask #1a).
        # Save/restore any user-set layout so (a) the baseline runs above
        # really were that layout, (b) later measurements see it again.
        if _budget_left() >= 240:
            prior_layout = os.environ.get("MXTPU_CONV_LAYOUT")
            os.environ["MXTPU_CONV_LAYOUT"] = "NHWC"
            try:
                ips_cl, _, _ = run_config(128, "bfloat16")
                extra["bf16_bs128_nhwc_imgs_per_sec"] = round(ips_cl, 2)
                extra["bf16_bs128_nhwc_mfu"] = _mfu(ips_cl)
            finally:
                if prior_layout is None:
                    os.environ.pop("MXTPU_CONV_LAYOUT", None)
                else:
                    os.environ["MXTPU_CONV_LAYOUT"] = prior_layout
        else:
            extra.setdefault("truncated_at", "nhwc_ab")
        if _budget_left() >= 180:
            extra["fp32_bs%d_per_step_dispatch" % BATCH] = round(
                run_per_step_fp32(BATCH), 2)
        # second flagship: transformer-LM tokens/s (new-capability
        # stack; never lets a failure sink the resnet record — errors
        # are caught here and run_transformer re-checks the budget
        # after its compile/warmup phase)
        # entry gate covers the fused-loop cost model: compile + one
        # K=8 warmup program + one timed program at the 30s/step
        # worst case, so the internal guard always fires before the
        # JSON record is at risk
        if _budget_left() >= 560:
            try:
                tps, tmfu, pallas = run_transformer()
                extra["transformer_lm_tokens_per_sec"] = tps
                extra["transformer_lm_mfu"] = tmfu
                extra["transformer_lm_pallas"] = pallas
            except Exception as e:
                extra["transformer_lm_error"] = str(e)[:300]
    result["extra"] = extra
    print(json.dumps(result))


if __name__ == "__main__":
    main()
