"""Benchmark: ResNet-50 training throughput (images/sec) on one chip.

Headline metric matches the reference's number (BASELINE.md: ResNet-50
training, bs=32, fp32 — 298.51 img/s on 1xV100, `docs/faq/perf.md:208-217`,
measured via the Module path of
`example/image-classification/train_imagenet.py` with synthetic data).

Same methodology here: the gluon model-zoo ResNet-50 is traced to a
Symbol, bound through Module/GraphExecutor — forward+backward compile to
ONE fused XLA module, the optimizer applies as ONE fused whole-tree
update — and timed over synthetic data.  Additional configs ride in the
same JSON line (the driver contract is ONE line):

  * bf16 (AMP compute policy, fp32 master weights) at bs=32 and bs=128 —
    the TPU-native analog of the reference's fp16 rows
    (`docs/faq/perf.md:166-176`: 2085 img/s inference bs32, 2355 bs128).
    NOTE: on TPU the fp32 path's matmuls/convs already run as bf16 MXU
    passes (jax Precision.DEFAULT), so AMP's win is HBM bandwidth, which
    only shows at larger batch: bf16@bs128 trains at ~2x the fp32@bs32
    rate, while bf16@bs32 is cast-overhead-bound;
  * an MFU estimate (12.3 GFLOP/img training cost, reference-standard
    ResNet-50 fwd ~4.1 GFLOP x3) against MXTPU_PEAK_TFLOPS.

Env knobs: MXTPU_BENCH_BATCH/WARMUP/ITERS/SKIP_EXTRA, MXTPU_PEAK_TFLOPS.
"""
import json
import os
import time

BASELINE_TRAIN_IMGS_PER_SEC = 298.51     # 1xV100 fp32 bs=32 (training)
BATCH = int(os.environ.get("MXTPU_BENCH_BATCH", "32"))
WARMUP = int(os.environ.get("MXTPU_BENCH_WARMUP", "3"))
ITERS = int(os.environ.get("MXTPU_BENCH_ITERS", "20"))
SKIP_EXTRA = os.environ.get("MXTPU_BENCH_SKIP_EXTRA", "0") == "1"
PEAK_TFLOPS = float(os.environ.get("MXTPU_PEAK_TFLOPS", "197"))
TRAIN_GFLOP_PER_IMG = 12.3


def run_config(batch, dtype):
    """Train-step throughput for one (batch, dtype) config; returns
    images/sec."""
    import numpy as np

    import mxtpu as mx
    from mxtpu import sym
    from mxtpu.gluon.model_zoo import vision
    from mxtpu.io.io import DataBatch

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()

    with mx.amp.scope(dtype if dtype != "float32" else None):
        net = vision.resnet50_v1(classes=1000)
        net.initialize(ctx=ctx)
        x_trace = mx.nd.zeros((batch, 3, 224, 224), ctx=ctx)
        out_sym, _, _ = net._trace_symbol(x_trace)
        softmax = sym.SoftmaxOutput(data=out_sym,
                                    label=sym.Variable("softmax_label"),
                                    name="softmax")

        mod = mx.mod.Module(softmax, data_names=("data0",),
                            label_names=("softmax_label",), context=ctx)
        mod.bind(data_shapes=[("data0", (batch, 3, 224, 224))],
                 label_shapes=[("softmax_label", (batch,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "momentum": 0.9})

    rng = np.random.RandomState(0)
    data = mx.nd.array(rng.rand(batch, 3, 224, 224).astype("float32"),
                       ctx=ctx)
    label = mx.nd.array(rng.randint(0, 1000, (batch,)).astype("float32"),
                        ctx=ctx)
    dbatch = DataBatch(data=[data], label=[label])

    def step():
        mod.forward(dbatch, is_train=True)
        mod.backward()
        mod.update()

    for _ in range(WARMUP):
        step()
    mx.nd.waitall()

    # best of 3 windows: the remote-tunnel chip has noisy latency
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            step()
        mx.nd.waitall()
        best = min(best, time.perf_counter() - t0)
    return batch * ITERS / best


def main():
    fp32 = run_config(BATCH, "float32")
    result = {
        "metric": "resnet50_train_imgs_per_sec_bs%d" % BATCH,
        "value": round(fp32, 2),
        "unit": "images/sec",
        "vs_baseline": round(fp32 / BASELINE_TRAIN_IMGS_PER_SEC, 3),
    }
    if not SKIP_EXTRA:
        extra = {}
        configs = [(BATCH, "bfloat16")]
        if BATCH != 128:
            configs.append((128, "bfloat16"))
        for batch, dtype in configs:
            ips = run_config(batch, dtype)
            extra["bf16_bs%d_imgs_per_sec" % batch] = round(ips, 2)
            extra["bf16_bs%d_mfu" % batch] = round(
                ips * TRAIN_GFLOP_PER_IMG / (PEAK_TFLOPS * 1e3), 4)
        extra["fp32_bs%d_mfu" % BATCH] = round(
            fp32 * TRAIN_GFLOP_PER_IMG / (PEAK_TFLOPS * 1e3), 4)
        result["extra"] = extra
    print(json.dumps(result))


if __name__ == "__main__":
    main()
