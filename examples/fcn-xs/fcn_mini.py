"""Fully-convolutional segmentation, miniature.

Analog of the reference's `example/fcn-xs/`: a conv encoder, a 1x1
score head, and a stride-2 Deconvolution (bilinear-initialized, the
FCN trick) upsampling back to input resolution; per-pixel softmax
cross-entropy.  Exercises dense prediction + transposed-conv
upsampling end to end.

Run:  python fcn_mini.py [--epochs 6]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

NUM_CLASSES = 3  # background, square, cross


def bilinear_kernel(channels, k):
    """FCN's bilinear upsampling initialization."""
    factor = (k + 1) // 2
    center = factor - 1 if k % 2 == 1 else factor - 0.5
    og = np.ogrid[:k, :k]
    filt = (1 - abs(og[0] - center) / factor) * \
        (1 - abs(og[1] - center) / factor)
    w = np.zeros((channels, channels, k, k), np.float32)
    for c in range(channels):
        w[c, c] = filt
    return w


class MiniFCN(gluon.nn.HybridBlock):
    def __init__(self):
        super().__init__()
        self.features = gluon.nn.HybridSequential()
        self.features.add(
            gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),                       # 16 -> 8
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"))
        self.score = gluon.nn.Conv2D(NUM_CLASSES, 1)
        self.up = gluon.nn.Conv2DTranspose(NUM_CLASSES, 4, strides=2,
                                           padding=1)

    def hybrid_forward(self, F, x):
        return self.up(self.score(self.features(x)))     # back to 16


def make_data(n, seed=0):
    rng = np.random.RandomState(seed)
    X = np.zeros((n, 1, 16, 16), np.float32)
    Y = np.zeros((n, 16, 16), np.float32)
    for i in range(n):
        c = rng.randint(1, NUM_CLASSES)
        size = rng.randint(5, 8)
        r0, c0 = rng.randint(0, 16 - size, 2)
        if c == 1:
            X[i, 0, r0:r0 + size, c0:c0 + size] = 1.0
            Y[i, r0:r0 + size, c0:c0 + size] = 1
        else:
            X[i, 0, r0 + size // 2, c0:c0 + size] = 1.0
            X[i, 0, r0:r0 + size, c0 + size // 2] = 1.0
            Y[i, r0 + size // 2, c0:c0 + size] = 2
            Y[i, r0:r0 + size, c0 + size // 2] = 2
        X[i] += rng.normal(0, 0.05, X[i].shape)
    return X, Y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = MiniFCN()
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    X, Y = make_data(256)
    net(nd.array(X[:1], ctx=ctx))  # materialize shapes
    net.up.weight.set_data(nd.array(bilinear_kernel(NUM_CLASSES, 4)))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=1)
    it = mx.io.NDArrayIter(X, Y.reshape(len(Y), -1),
                           batch_size=args.batch_size, shuffle=True,
                           label_name="label")
    for epoch in range(args.epochs):
        it.reset()
        tot = n = 0.0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].reshape((-1, 16, 16)).as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            tot += float(loss.mean().asnumpy())
            n += 1
        logging.info("epoch %d pixel CE %.4f", epoch, tot / n)

    pred = net(nd.array(X[:64], ctx=ctx)).asnumpy().argmax(axis=1)
    piou = []
    for c in range(1, NUM_CLASSES):
        inter = ((pred == c) & (Y[:64] == c)).sum()
        union = ((pred == c) | (Y[:64] == c)).sum()
        if union:
            piou.append(inter / union)
    miou = float(np.mean(piou))
    pix_acc = float((pred == Y[:64]).mean())
    logging.info("pixel accuracy %.3f   mIoU(fg) %.3f", pix_acc, miou)
    # both bars matter: pixel accuracy alone is satisfiable by an
    # all-background predictor (~90% of pixels); foreground IoU proves
    # the upsampled head actually localizes objects
    assert pix_acc > 0.9, "dense prediction should fit the shapes"
    assert miou > 0.1, "foreground IoU must beat a degenerate predictor"


if __name__ == "__main__":
    main()
