"""Multivariate time-series forecasting — the reference's
`example/multivariate_time_series/` (LSTNet, Lai et al. 2018) in
miniature: conv feature extraction over the lookback window, a GRU
over conv features, and the crucial autoregressive highway that LSTNet
adds so scale changes aren't lost — vs a naive last-value baseline
(relative RSE metric, as the paper reports).

Synthetic data: 6 correlated series with different periods + trend +
noise.

Run:  python lstnet_mini.py [--epochs 12]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

N_SERIES = 6
WINDOW = 24
HORIZON = 3


def make_series(rng, t_len=900):
    t = np.arange(t_len)
    base = np.stack([np.sin(2 * np.pi * t / p) for p in
                     (12, 24, 16, 24, 8, 32)], 1)
    mix = rng.uniform(0.3, 1.0, (N_SERIES, N_SERIES))
    xs = base @ mix + 0.001 * t[:, None] + 0.05 * rng.randn(t_len,
                                                            N_SERIES)
    return xs.astype(np.float32)


def windows(xs):
    X, Y = [], []
    for i in range(len(xs) - WINDOW - HORIZON):
        X.append(xs[i:i + WINDOW])
        Y.append(xs[i + WINDOW + HORIZON - 1])
    return np.stack(X), np.stack(Y)


class LSTNetMini(gluon.nn.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.conv = gluon.nn.Conv1D(16, 6, activation="relu")
            self.gru = gluon.rnn.GRU(24, num_layers=1)
            self.fc = gluon.nn.Dense(N_SERIES)
            self.ar = gluon.nn.Dense(1, flatten=False)  # per-series AR

    def hybrid_forward(self, F, x):
        # x: (B, W, S); conv over time
        c = self.conv(x.transpose((0, 2, 1)))          # (B, 16, W')
        h = self.gru(c.transpose((2, 0, 1)))           # (T, B, 24)
        nn_out = self.fc(h[-1])                        # (B, S)
        # AR highway over the last 8 steps of each series
        ar_in = x[:, -8:, :].transpose((0, 2, 1))      # (B, S, 8)
        ar_out = self.ar(ar_in).reshape((0, -1))       # (B, S)
        return nn_out + ar_out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=17)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    xs = make_series(rng)
    X, Y = windows(xs)
    n_train = int(len(X) * 0.8)
    Xtr, Ytr = X[:n_train], Y[:n_train]
    Xte, Yte = X[n_train:], Y[n_train:]

    net = LSTNetMini()
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    it = mx.io.NDArrayIter(Xtr, Ytr, batch_size=args.batch_size,
                           shuffle=True)

    for epoch in range(args.epochs):
        it.reset()
        lsum = n = 0.0
        for batch in it:
            xb = batch.data[0]
            yb = batch.label[0]
            with autograd.record():
                pred = net(xb)
                loss = ((pred - yb) ** 2).mean()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
            n += 1
        pred = net(nd.array(Xte)).asnumpy()
        rse = np.sqrt(((pred - Yte) ** 2).sum()) / \
            np.sqrt(((Yte - Yte.mean()) ** 2).sum())
        naive = np.sqrt(((Xte[:, -1] - Yte) ** 2).sum()) / \
            np.sqrt(((Yte - Yte.mean()) ** 2).sum())
        logging.info("epoch %d train mse %.4f test RSE %.3f "
                     "(naive %.3f)", epoch, lsum / n, rse, naive)
    print("FINAL_RSE %.4f" % rse)


if __name__ == "__main__":
    main()
