"""Variational autoencoder.

Analog of the reference's `example/bayesian-methods` / `vae-gan`
family: encoder emits (mu, log-var), the reparameterization trick
samples the code, and the loss is reconstruction BCE + KL(q||N(0,1)).
Exercises `mx.random.normal` inside an autograd scope (the
reparameterized sample is differentiable through mu/sigma).

Run:  python vae_mnist.py [--epochs 5] [--latent 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


class VAE(gluon.nn.HybridBlock):
    def __init__(self, latent=8, hidden=128):
        super().__init__()
        self.latent = latent
        self.enc = gluon.nn.HybridSequential()
        self.enc.add(gluon.nn.Dense(hidden, activation="relu"),
                     gluon.nn.Dense(2 * latent))
        self.dec = gluon.nn.HybridSequential()
        self.dec.add(gluon.nn.Dense(hidden, activation="relu"),
                     gluon.nn.Dense(28 * 28, activation="sigmoid"))

    def hybrid_forward(self, F, x, eps):
        h = self.enc(x)
        mu = F.slice_axis(h, axis=1, begin=0, end=self.latent)
        logvar = F.slice_axis(h, axis=1, begin=self.latent,
                              end=2 * self.latent)
        z = mu + F.exp(0.5 * logvar) * eps   # reparameterization
        return self.dec(z), mu, logvar


def synthetic_blobs(n=512, seed=0):
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[:28, :28]
    out = np.zeros((n, 784), np.float32)
    for i in range(n):
        cx, cy, r = rng.randint(8, 20), rng.randint(8, 20), \
            rng.randint(3, 8)
        out[i] = (((yy - cy) ** 2 + (xx - cx) ** 2) < r * r) \
            .astype(np.float32).ravel()
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--latent", type=int, default=8)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = VAE(args.latent)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    X = synthetic_blobs()
    it = mx.io.NDArrayIter(X, batch_size=args.batch_size, shuffle=True)
    first = last = None
    for epoch in range(args.epochs):
        it.reset()
        total = n = 0.0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            eps = mx.random.normal(0, 1, (x.shape[0], args.latent),
                                   ctx=ctx)
            with autograd.record():
                xhat, mu, logvar = net(x, eps)
                bce = -(x * (xhat + 1e-7).log() +
                        (1 - x) * (1 - xhat + 1e-7).log()).sum(axis=1)
                kl = -0.5 * (1 + logvar - mu * mu -
                             logvar.exp()).sum(axis=1)
                loss = (bce + kl).mean()
            loss.backward()
            trainer.step(x.shape[0])
            total += float(loss.asnumpy())
            n += 1
        if first is None:
            first = total / n
        last = total / n
        logging.info("epoch %d ELBO loss %.2f", epoch, last)
    assert last < first, "ELBO loss should decrease"
    # decode a prior sample
    z = mx.random.normal(0, 1, (4, args.latent), ctx=ctx)
    gen = net.dec(z)
    logging.info("prior samples decoded: %s", gen.shape)


if __name__ == "__main__":
    main()
