"""Named-entity recognition, miniature — the reference's
`example/named_entity_recognition/` role: a BiLSTM token tagger with
BIO labels, per-entity evaluation, and masked loss over padded
sequences.

Synthetic task: sentences over a 120-token vocab; tokens 100-109 start
a two-token PERSON mention (B-PER, I-PER), tokens 110-119 a one-token
LOC mention; everything else is O.  The tagger must learn both the
trigger tokens and the positional continuation rule.

Run:  python ner_bilstm.py [--epochs 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

VOCAB = 120
TAGS = 5          # O, B-PER, I-PER, B-LOC, PAD
O, BPER, IPER, BLOC, PAD = range(5)
MAX_LEN = 20


def make_sentence(rng):
    toks, tags = [], []
    while len(toks) < MAX_LEN - 1:
        r = rng.rand()
        if r < 0.12:
            toks += [rng.randint(100, 110), rng.randint(0, 100)]
            tags += [BPER, IPER]
        elif r < 0.22:
            toks.append(rng.randint(110, 120))
            tags.append(BLOC)
        else:
            toks.append(rng.randint(0, 100))
            tags.append(O)
        if rng.rand() < 0.08:
            break
    toks, tags = toks[:MAX_LEN], tags[:MAX_LEN]
    n = len(toks)
    toks += [0] * (MAX_LEN - n)
    tags += [PAD] * (MAX_LEN - n)
    return toks, tags, n


def make_batch(rng, bs):
    t, g, n = zip(*[make_sentence(rng) for _ in range(bs)])
    return (np.array(t, np.float32), np.array(g, np.float32),
            np.array(n, np.float32))


class Tagger(gluon.nn.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.emb = gluon.nn.Embedding(VOCAB, 32)
            self.rnn = gluon.rnn.LSTM(32, num_layers=1,
                                      bidirectional=True)
            self.out = gluon.nn.Dense(TAGS, flatten=False)

    def hybrid_forward(self, F, x):
        h = self.rnn(self.emb(x).transpose((1, 0, 2)))
        return self.out(h).transpose((1, 0, 2))  # (B, T, TAGS)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    model = Tagger()
    model.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        lsum = 0.0
        for _ in range(20):
            x, y, n = make_batch(rng, args.batch_size)
            mask = (np.arange(MAX_LEN)[None, :] <
                    n[:, None]).astype(np.float32)
            with autograd.record():
                logits = model(nd.array(x))
                l = loss_fn(logits, nd.array(y),
                            nd.array(mask[..., None]))
                loss = l.sum() / mask.sum()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
        # entity-level precision/recall on fresh data
        x, y, n = make_batch(rng, 64)
        pred = model(nd.array(x)).asnumpy().argmax(-1)
        mask = np.arange(MAX_LEN)[None, :] < n[:, None]
        tp = int(((pred == y) & mask & (y != O)).sum())
        fp = int(((pred != y) & mask & (pred != O)).sum())
        fn = int(((pred != y) & mask & (y != O)).sum())
        prec = tp / max(tp + fp, 1)
        rec = tp / max(tp + fn, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-9)
        logging.info("epoch %d loss %.4f entity F1 %.3f", epoch,
                     lsum / 20, f1)
    print("FINAL_F1 %.4f" % f1)


if __name__ == "__main__":
    main()
