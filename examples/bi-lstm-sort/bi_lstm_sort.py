"""Sorting digit sequences with a bidirectional LSTM.

Analog of the reference's `example/bi-lstm-sort/`: the network reads a
sequence of digits and emits the same digits sorted — learned purely
from examples.  Exercises the gluon rnn layer stack (bidirectional
LSTM via two directions) and per-step Dense decoding; the recurrence
compiles to `lax.scan` (`mxtpu/gluon/rnn`).

Run:  python bi_lstm_sort.py [--epochs 10] [--seq-len 5]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


class BiLSTMSort(gluon.nn.HybridBlock):
    def __init__(self, vocab=10, hidden=64):
        super().__init__()
        self.embed = gluon.nn.Embedding(vocab, 16)
        self.fwd = gluon.rnn.LSTM(hidden, layout="NTC")
        self.bwd = gluon.rnn.LSTM(hidden, layout="NTC")
        self.proj = gluon.nn.Dense(vocab, flatten=False)

    def hybrid_forward(self, F, x):
        e = self.embed(x)                       # (N, T, E)
        h_f = self.fwd(e)
        h_b = F.reverse(self.bwd(F.reverse(e, axis=1)), axis=1)
        return self.proj(F.concat(h_f, h_b, dim=2))  # (N, T, vocab)


def make_data(n=2048, seq_len=5, vocab=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab, (n, seq_len))
    y = np.sort(x, axis=1)
    return x.astype(np.float32), y.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=5)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    X, Y = make_data(seq_len=args.seq_len)
    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = BiLSTMSort()
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    it = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size,
                           shuffle=True)
    acc = 0.0
    for epoch in range(args.epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            pred = out.asnumpy().argmax(axis=-1)
            correct += (pred == y.asnumpy()).sum()
            total += pred.size
        acc = correct / total
        logging.info("epoch %d per-position accuracy %.3f", epoch, acc)
    assert acc > 0.85, "bi-LSTM should learn to sort short sequences"


if __name__ == "__main__":
    main()
