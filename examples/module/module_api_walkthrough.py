"""Module API walkthrough — the reference's `example/module/` role
(mnist_mlp.py / sequential_module.py): the intermediate-level Module
interface end to end — bind/init/fit on a DataIter, score with a
metric, per-batch forward/backward with manual update, checkpoint
save/load + resume, and predict — on a synthetic separable task.

Run:  python module_api_walkthrough.py [--epochs 5]
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import sym


def make_data(rng, W, n=800, dim=20):
    # train and val must share the SAME ground-truth W
    X = rng.randn(n, dim).astype(np.float32)
    y = (X @ W + 0.3 * rng.randn(n, W.shape[1])).argmax(1) \
        .astype(np.float32)
    return X, y


def build_symbol():
    data = sym.Variable("data")
    h = sym.FullyConnected(data=data, num_hidden=32, name="fc1")
    h = sym.Activation(data=h, act_type="relu")
    h = sym.FullyConnected(data=h, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(data=h, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=50)
    ap.add_argument("--seed", type=int, default=23)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    W_true = rng.randn(20, 4) * 2
    X, y = make_data(rng, W_true)
    Xv, yv = make_data(rng, W_true, n=200)

    train_it = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                                 shuffle=True,
                                 label_name="softmax_label")
    val_it = mx.io.NDArrayIter(Xv, yv, batch_size=args.batch_size,
                               label_name="softmax_label")

    # --- 1) high-level fit ---
    mod = mx.mod.Module(build_symbol(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train_it, eval_data=val_it, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            num_epoch=args.epochs,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 10))
    metric = mx.metric.Accuracy()
    mod.score(val_it, metric)
    logging.info("fit accuracy %.3f", metric.get()[1])

    # --- 2) checkpoint + resume ---
    with tempfile.TemporaryDirectory() as td:
        prefix = os.path.join(td, "mod")
        mod.save_checkpoint(prefix, args.epochs)
        s2, arg2, aux2 = mx.model.load_checkpoint(prefix, args.epochs)
        mod2 = mx.mod.Module(s2, data_names=("data",),
                             label_names=("softmax_label",))
        mod2.bind(data_shapes=train_it.provide_data,
                  label_shapes=train_it.provide_label)
        mod2.set_params(arg2, aux2)
        metric.reset()
        mod2.score(val_it, metric)
        logging.info("resumed accuracy %.3f", metric.get()[1])

    # --- 3) low-level forward/backward loop ---
    mod3 = mx.mod.Module(build_symbol(), data_names=("data",),
                         label_names=("softmax_label",))
    mod3.bind(data_shapes=train_it.provide_data,
              label_shapes=train_it.provide_label)
    mod3.init_params()
    mod3.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1})
    train_it.reset()
    for batch in train_it:
        mod3.forward(batch, is_train=True)
        mod3.backward()
        mod3.update()
    metric.reset()
    mod3.score(val_it, metric)
    logging.info("manual-loop accuracy %.3f", metric.get()[1])

    # --- 4) predict ---
    preds = mod.predict(val_it)
    acc = float((preds.asnumpy().argmax(1) == yv).mean())
    print("FINAL_ACCURACY %.4f" % acc)


if __name__ == "__main__":
    main()
