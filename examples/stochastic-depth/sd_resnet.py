"""Stochastic-depth ResNet (Huang et al. 2016).

Analog of the reference's `example/stochastic-depth/sd_cifar10.py`:
residual blocks are randomly dropped during training with linearly
decaying survival probability; at inference every block runs, scaled
by its survival rate.  Shows mode-dependent control flow done the XLA
way — the drop decision is a Bernoulli draw multiplied into the branch
(no Python branching inside the compiled step).

Run:  python sd_resnet.py [--epochs 4] [--death-rate 0.5]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


class SDResidual(gluon.nn.HybridBlock):
    def __init__(self, channels, survival_p):
        super().__init__()
        self.survival_p = survival_p
        self.body = gluon.nn.HybridSequential()
        self.body.add(
            gluon.nn.Conv2D(channels, 3, padding=1, activation="relu"),
            gluon.nn.Conv2D(channels, 3, padding=1))

    def hybrid_forward(self, F, x):
        out = self.body(x)
        if autograd.is_training():
            # one Bernoulli gate per batch (paper's per-sample variant
            # works too; per-batch matches the reference example)
            gate = float(np.random.rand() < self.survival_p)
            return F.Activation(x + gate * out, act_type="relu")
        return F.Activation(x + self.survival_p * out, act_type="relu")


class SDNet(gluon.nn.HybridBlock):
    def __init__(self, num_blocks=6, channels=16, classes=10,
                 death_rate=0.5):
        super().__init__()
        self.stem = gluon.nn.Conv2D(channels, 3, padding=1,
                                    activation="relu")
        self.blocks = gluon.nn.HybridSequential()
        for i in range(num_blocks):
            # linearly decaying survival: earlier blocks survive more
            p = 1.0 - death_rate * (i + 1) / num_blocks
            self.blocks.add(SDResidual(channels, p))
        self.head = gluon.nn.HybridSequential()
        self.head.add(gluon.nn.GlobalAvgPool2D(), gluon.nn.Flatten(),
                      gluon.nn.Dense(classes))

    def hybrid_forward(self, F, x):
        return self.head(self.blocks(self.stem(x)))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--death-rate", type=float, default=0.5)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    rng = np.random.RandomState(0)
    # low-frequency class templates (smooth gradients survive the
    # global average pooling head)
    yy, xx = np.mgrid[:16, :16] / 16.0
    templates = np.stack([
        np.stack([np.cos(2 * np.pi * (k * yy / 10 + c / 3)) for c in
                  range(3)]) for k in range(10)]).astype(np.float32)
    y = rng.randint(0, 10, 1024)
    X = templates[y] + rng.normal(0, 0.1, (1024, 3, 16, 16)) \
        .astype(np.float32)
    it = mx.io.NDArrayIter(X, y.astype(np.float32),
                           batch_size=args.batch_size, shuffle=True)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = SDNet(death_rate=args.death_rate)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    for epoch in range(args.epochs):
        it.reset()
        metric = mx.metric.Accuracy()
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            yb = batch.label[0].as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, yb)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([yb], [out])
        logging.info("epoch %d train acc %.3f", epoch, metric.get()[1])
    # inference path (expected-depth scaling) still classifies
    ev = mx.metric.Accuracy()
    it.reset()
    for batch in it:
        ev.update([batch.label[0].as_in_context(ctx)],
                  [net(batch.data[0].as_in_context(ctx))])
    logging.info("inference accuracy %.3f", ev.get()[1])
    assert ev.get()[1] > 0.6


if __name__ == "__main__":
    main()
