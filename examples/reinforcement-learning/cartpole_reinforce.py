"""REINFORCE policy gradient on CartPole.

Analog of the reference's `example/reinforcement-learning/` family.
No gym in this image, so a faithful 30-line CartPole (standard
Barto-Sutton dynamics, same termination bounds) is included.  The
policy is a gluon MLP; the REINFORCE step weights log-prob gradients by
normalized discounted returns.

Run:  python cartpole_reinforce.py [--episodes 150]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


class CartPole(object):
    """Classic cart-pole dynamics (Euler, dt=0.02)."""

    def __init__(self, seed=0):
        self.rng = np.random.RandomState(seed)

    def reset(self):
        self.s = self.rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        return self.s.copy()

    def step(self, action):
        x, x_dot, th, th_dot = self.s
        force = 10.0 if action == 1 else -10.0
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + 0.05 * th_dot ** 2 * sin) / 1.1
        th_acc = (9.8 * sin - cos * temp) / \
            (0.5 * (4.0 / 3.0 - 0.1 * cos ** 2 / 1.1))
        x_acc = temp - 0.05 * th_acc * cos / 1.1
        dt = 0.02
        self.s = np.array([x + dt * x_dot, x_dot + dt * x_acc,
                           th + dt * th_dot, th_dot + dt * th_acc],
                          np.float32)
        done = bool(abs(self.s[0]) > 2.4 or abs(self.s[2]) > 0.2095)
        return self.s.copy(), 1.0, done


def discounted_returns(rewards, gamma):
    out = np.zeros(len(rewards), np.float32)
    acc = 0.0
    for i in reversed(range(len(rewards))):
        acc = rewards[i] + gamma * acc
        out[i] = acc
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", type=int, default=150)
    p.add_argument("--gamma", type=float, default=0.99)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--max-steps", type=int, default=200)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    ctx = mx.cpu()  # per-step env interaction: host-latency bound
    policy = gluon.nn.HybridSequential()
    policy.add(gluon.nn.Dense(32, activation="relu"),
               gluon.nn.Dense(2))
    policy.initialize(mx.initializer.Xavier(), ctx=ctx)
    trainer = gluon.Trainer(policy.collect_params(), "adam",
                            {"learning_rate": args.lr})
    env = CartPole()
    rng = np.random.RandomState(1)
    recent = []
    for ep in range(args.episodes):
        states, actions, rewards = [], [], []
        s = env.reset()
        for _ in range(args.max_steps):
            logits = policy(nd.array(s[None], ctx=ctx)).asnumpy()[0]
            prob = np.exp(logits - logits.max())
            prob /= prob.sum()
            a = rng.choice(2, p=prob)
            states.append(s)
            actions.append(a)
            s, r, done = env.step(a)
            rewards.append(r)
            if done:
                break
        ret = discounted_returns(rewards, args.gamma)
        ret = (ret - ret.mean()) / (ret.std() + 1e-6)
        S = nd.array(np.stack(states), ctx=ctx)
        A = nd.array(np.asarray(actions, np.float32), ctx=ctx)
        R = nd.array(ret, ctx=ctx)
        with autograd.record():
            logits = policy(S)
            logp = nd.log_softmax(logits, axis=-1)
            chosen = nd.pick(logp, A, axis=1)
            loss = -(chosen * R).mean()
        loss.backward()
        trainer.step(1)
        recent.append(len(rewards))
        if (ep + 1) % 25 == 0:
            logging.info("episode %d  mean length (last 25): %.1f",
                         ep + 1, np.mean(recent[-25:]))
    early = np.mean(recent[:25])
    late = np.mean(recent[-25:])
    logging.info("mean episode length: first25=%.1f last25=%.1f",
                 early, late)
    assert late > early, "policy should improve with REINFORCE"


if __name__ == "__main__":
    main()
