"""Noise-contrastive estimation for a large-softmax word model.

Analog of the reference's `example/nce-loss/`: instead of a full-vocab
softmax, each positive target is scored against k noise words drawn
from the unigram distribution, turning the LM step into k+1 binary
classifications.  The output table uses sparse_grad Embedding lookups,
so a step touches only the k+1 sampled rows — the same reason the
reference pairs NCE with row_sparse weights.

Run:  python nce_lm.py [--epochs 8] [--num-noise 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


class NCEModel(gluon.nn.HybridBlock):
    def __init__(self, vocab, dim):
        super().__init__()
        self.in_embed = gluon.nn.Embedding(vocab, dim, sparse_grad=True)
        self.out_embed = gluon.nn.Embedding(vocab, dim, sparse_grad=True)
        self.out_bias = gluon.nn.Embedding(vocab, 1, sparse_grad=True)

    def hybrid_forward(self, F, context, candidates):
        """context (N,), candidates (N, 1+k): [target | noise...].
        Returns logits (N, 1+k)."""
        h = self.in_embed(context)              # (N, D)
        w = self.out_embed(candidates)          # (N, 1+k, D)
        b = self.out_bias(candidates)           # (N, 1+k, 1)
        return F.sum(w * F.expand_dims(h, axis=1), axis=-1) + \
            F.Reshape(b, shape=(0, -1))


def make_bigrams(vocab=500, n=4096, seed=0):
    """Deterministic bigram structure: next = (w*7 + 3) % vocab."""
    rng = np.random.RandomState(seed)
    ctx_w = rng.randint(0, vocab, n)
    target = (ctx_w * 7 + 3) % vocab
    return ctx_w.astype(np.float32), target


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--vocab", type=int, default=500)
    p.add_argument("--dim", type=int, default=32)
    p.add_argument("--num-noise", type=int, default=8)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctxs, targets = make_bigrams(args.vocab)
    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = NCEModel(args.vocab, args.dim)
    net.initialize(mx.initializer.Normal(0.1), ctx=ctx)
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss(from_sigmoid=False)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    n = len(ctxs)
    rng = np.random.RandomState(1)
    first = last = None
    for epoch in range(args.epochs):
        order = rng.permutation(n)
        total = nb = 0.0
        for s in range(0, n - args.batch_size + 1, args.batch_size):
            idx = order[s:s + args.batch_size]
            noise = rng.randint(0, args.vocab,
                                (len(idx), args.num_noise))
            cand = np.concatenate([targets[idx][:, None], noise], axis=1)
            labels = np.zeros_like(cand, dtype=np.float32)
            labels[:, 0] = 1.0  # the true bigram continuation
            c = nd.array(ctxs[idx], ctx=ctx)
            k = nd.array(cand.astype(np.float32), ctx=ctx)
            y = nd.array(labels, ctx=ctx)
            with autograd.record():
                logits = net(c, k)
                loss = loss_fn(logits, y)
            loss.backward()
            trainer.step(len(idx))
            total += float(loss.mean().asnumpy())
            nb += 1
        if first is None:
            first = total / nb
        last = total / nb
        logging.info("epoch %d NCE loss %.4f", epoch, last)
    assert last < first * 0.7, "NCE loss should drop on bigram structure"


if __name__ == "__main__":
    main()
