#!/usr/bin/env python
"""Word-level LSTM language model — the analog of the reference's
`example/rnn/word_lm/train.py` (BASELINE config #3's named deliverable):
a stateful Module-path LM with truncated BPTT, hidden state carried
across batches within an epoch, optional tied embedding/output weights
(`--tied`), global-norm gradient clipping, and per-epoch train/valid
perplexity with lr annealing on plateau — the reference's training
recipe (train.py:61-118), TPU-native.

Corpus: `--text FILE` (whitespace-tokenized, reference data.py Corpus
role) or a seeded synthetic Markov corpus with learnable structure so
perplexity provably drops.

Run:  python train.py --epochs 4 [--tied]
"""
import argparse
import logging
import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


class Corpus(object):
    """Reference word_lm/data.py Corpus: builds a vocab and a flat
    token stream; here from a file or the synthetic generator."""

    def __init__(self, path=None, n_tokens=60000, vocab=200, seed=3):
        if path:
            with open(path) as f:
                words = f.read().split()
            uniq = sorted(set(words))
            self.vocab = {w: i for i, w in enumerate(uniq)}
            self.data = np.array([self.vocab[w] for w in words], np.int64)
        else:
            rng = np.random.RandomState(seed)
            toks = [rng.randint(1, vocab)]
            for _ in range(n_tokens - 1):
                toks.append((toks[-1] * 7 + 3) % vocab
                            if rng.rand() < 0.85 else rng.randint(0, vocab))
            self.vocab = {i: i for i in range(vocab)}
            self.data = np.array(toks, np.int64)

    def batchify(self, batch_size):
        nb = len(self.data) // batch_size
        return self.data[:nb * batch_size].reshape(
            batch_size, nb).T  # (nbatch, batch_size)


class RNNModel(gluon.nn.HybridBlock):
    """Embedding -> n-layer LSTM -> (tied) decoder (reference
    word_lm/model.py rnn())."""

    def __init__(self, vocab_size, emsize, nhid, nlayers, dropout,
                 tied, **kw):
        super().__init__(**kw)
        self.nhid, self.nlayers = nhid, nlayers
        with self.name_scope():
            self.drop = gluon.nn.Dropout(dropout)
            self.encoder = gluon.nn.Embedding(vocab_size, emsize)
            self.rnn = gluon.rnn.LSTM(nhid, num_layers=nlayers,
                                      dropout=dropout)
            if tied:
                if nhid != emsize:
                    raise ValueError("--tied requires emsize == nhid")
                self.decoder = gluon.nn.Dense(
                    vocab_size, flatten=False,
                    params=self.encoder.params)
            else:
                self.decoder = gluon.nn.Dense(vocab_size, flatten=False)

    def hybrid_forward(self, F, x, states):
        # x: (bptt, batch)
        emb = self.drop(self.encoder(x))
        out, states = self.rnn(emb, states)
        return self.decoder(self.drop(out)), states

    def begin_state(self, batch_size, ctx):
        return self.rnn.begin_state(batch_size=batch_size, ctx=ctx)


def detach(states):
    return [s.detach() for s in states]


def clip_global_norm(params, max_norm):
    grads = [p.grad() for p in params.values() if p.grad_req != "null"]
    total = math.sqrt(sum(float((g ** 2).sum().asnumpy())
                          for g in grads))
    if total > max_norm:
        scale = max_norm / total
        for g in grads:
            g *= scale
    return total


def run_epoch(model, data, bptt, loss_fn, trainer=None, clip=0.25):
    ctx = mx.cpu()
    batch_size = data.shape[1]
    states = model.begin_state(batch_size, ctx)
    total_loss, n = 0.0, 0
    for i in range(0, data.shape[0] - 1 - bptt, bptt):
        x = nd.array(data[i:i + bptt])
        y = nd.array(data[i + 1:i + 1 + bptt])
        states = detach(states)  # truncated BPTT boundary
        if trainer is not None:
            with autograd.record():
                logits, states = model(x, states)
                loss = loss_fn(logits, y).mean()
            loss.backward()
            clip_global_norm(model.collect_params(), clip)
            trainer.step(1)
        else:
            logits, states = model(x, states)
            loss = loss_fn(logits, y).mean()
        total_loss += float(loss.asnumpy())
        n += 1
    return total_loss / max(n, 1)


def main():
    ap = argparse.ArgumentParser(
        description="Word-level LSTM language model")
    ap.add_argument("--text", default=None)
    ap.add_argument("--emsize", type=int, default=64)
    ap.add_argument("--nhid", type=int, default=64)
    ap.add_argument("--nlayers", type=int, default=2)
    # mean-normalized loss needs a large SGD lr (the classic recipe)
    ap.add_argument("--lr", type=float, default=20.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--dropout", type=float, default=0.2)
    ap.add_argument("--tied", action="store_true")
    ap.add_argument("--bptt", type=int, default=20)
    ap.add_argument("--seed", type=int, default=3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)

    corpus = Corpus(args.text, seed=args.seed)
    vocab_size = max(len(corpus.vocab), int(corpus.data.max()) + 1)
    stream = corpus.batchify(args.batch_size)
    n_train = int(stream.shape[0] * 0.9)
    train_data, valid_data = stream[:n_train], stream[n_train:]

    model = RNNModel(vocab_size, args.emsize, args.nhid, args.nlayers,
                     args.dropout, args.tied)
    model.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    best_val = float("inf")
    for epoch in range(args.epochs):
        train_loss = run_epoch(model, train_data, args.bptt, loss_fn,
                               trainer, args.clip)
        val_loss = run_epoch(model, valid_data, args.bptt, loss_fn)
        logging.info(
            "epoch %d train ppl %.2f valid ppl %.2f lr %.3f", epoch,
            math.exp(train_loss), math.exp(val_loss),
            trainer.learning_rate)
        if val_loss < best_val:
            best_val = val_loss
        else:  # reference recipe: anneal lr when valid stops improving
            trainer.set_learning_rate(trainer.learning_rate * 0.25)
    print("FINAL_VALID_PPL %.3f" % math.exp(best_val))


if __name__ == "__main__":
    main()
