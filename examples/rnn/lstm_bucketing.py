#!/usr/bin/env python
"""LSTM language model with BucketingModule.

The analog of the reference's `example/rnn/bucketing/lstm_bucketing.py`
(BASELINE.json config #3): variable-length sequences bucketed into a few
fixed lengths, one compiled XLA module per bucket, shared weights.

Runs on synthetic token sequences by default (pass --text for a corpus
file, one sentence per line, whitespace-tokenized).
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import sym
from mxtpu.rnn import BucketSentenceIter, LSTMCell, SequentialRNNCell


def synthetic_sentences(n=2000, vocab=100, seed=0):
    """Markov-ish synthetic corpus: next token = (tok*3+1) % vocab with
    noise — learnable structure so perplexity drops."""
    rng = np.random.RandomState(seed)
    sents = []
    for _ in range(n):
        length = rng.randint(5, 33)
        toks = [rng.randint(1, vocab)]
        for _ in range(length - 1):
            toks.append((toks[-1] * 3 + 1) % vocab
                        if rng.rand() < 0.9 else rng.randint(1, vocab))
        sents.append(toks)
    return sents, vocab


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--buckets", default="8,16,24,32")
    ap.add_argument("--text", default=None)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    if args.text:
        vocab_map = {}
        sents = []
        for line in open(args.text):
            toks = []
            for w in line.split():
                toks.append(vocab_map.setdefault(w, len(vocab_map) + 1))
            if len(toks) > 1:
                sents.append(toks)
        vocab = len(vocab_map) + 1
    else:
        sents, vocab = synthetic_sentences()

    buckets = [int(b) for b in args.buckets.split(",")]
    train = BucketSentenceIter(sents, args.batch_size, buckets=buckets,
                               invalid_label=0)

    def sym_gen(seq_len):
        data = sym.Variable("data")
        label = sym.Variable("softmax_label")
        embed = sym.Embedding(data=data, input_dim=vocab,
                              output_dim=args.num_embed, name="embed")
        stack = SequentialRNNCell()
        for i in range(args.num_layers):
            stack.add(LSTMCell(num_hidden=args.num_hidden,
                               prefix="lstm_l%d_" % i))
        outputs, _ = stack.unroll(seq_len, inputs=embed, layout="NTC",
                                  merge_outputs=True,
                                  batch_size=args.batch_size)
        pred = sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = sym.FullyConnected(data=pred, num_hidden=vocab, name="pred")
        flat_label = sym.Reshape(data=label, shape=(-1,))
        pred = sym.SoftmaxOutput(data=pred, label=flat_label,
                                 name="softmax")
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(
        sym_gen, default_bucket_key=train.default_bucket_key,
        context=mx.tpu() if mx.num_tpus() else mx.cpu())
    mod.fit(train, eval_metric=mx.metric.Perplexity(ignore_label=0),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       20))
    ppl = mod.score(train, mx.metric.Perplexity(ignore_label=0))[0][1]
    logging.info("final train perplexity: %.2f", ppl)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
