#!/usr/bin/env python
"""Large-vocabulary LSTM language model with SAMPLED SOFTMAX — the
analog of the reference's `example/rnn/large_word_lm/train.py`
(Jozefowicz et al. importance-sampled softmax; the reference's
LogUniformGenerator C++ sampler is the framework op
`_sample_unique_zipfian` here — Gumbel-top-k on TPU instead of
rejection sampling).

Training never materializes the (B*T, V) logits: each step scores the
true class plus `--num-samples` shared log-uniform negatives, with the
importance correction  logit_c - log(E[count_c])  (reference
model.py:74-118 sampled_softmax), so vocab size drops out of the
training cost.  Evaluation uses the exact full softmax perplexity.

Corpus: synthetic Zipf-weighted Markov chain over a 10k vocabulary —
structure is learnable and the unigram distribution matches the
log-uniform sampler's assumption, like real text.

Run:  python train.py --epochs 3
"""
import argparse
import logging
import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

V = 10000


def make_corpus(rng, n_tokens=40000):
    """Zipfian unigrams + deterministic bigram structure."""
    # Zipf-ish marginal via the same log-uniform form the sampler uses
    toks = [1]
    for _ in range(n_tokens - 1):
        if rng.rand() < 0.75:
            toks.append((toks[-1] * 13 + 7) % V)   # learnable successor
        else:
            toks.append(min(int(np.exp(rng.uniform(0, np.log(V))) - 1),
                            V - 1))                # zipf noise
    return np.array(toks, np.int64)


class RNNLM(gluon.nn.HybridBlock):
    def __init__(self, emsize, nhid, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.encoder = gluon.nn.Embedding(V, emsize)
            self.rnn = gluon.rnn.LSTM(nhid)
            # decoder weight/bias live as free Parameters: the sampled
            # softmax gathers ROWS of them instead of running Dense
            self.dec_w = self.params.get("dec_weight", shape=(V, nhid),
                                         init=mx.init.Xavier())
            self.dec_b = self.params.get("dec_bias", shape=(V,),
                                         init="zeros")

    def hybrid_forward(self, F, x, states, dec_w, dec_b):
        emb = self.encoder(x)                      # (T, B, E)
        out, states = self.rnn(emb, states)        # (T, B, H)
        return out, states

    def begin_state(self, batch_size, ctx):
        return self.rnn.begin_state(batch_size=batch_size, ctx=ctx)


def log_expected_count(classes, num_tries):
    """E[count_c] under the log-uniform distribution for `num_tries`
    unique draws (reference LogUniformGenerator.expected_count)."""
    p = nd.log((classes + 2.0) / (classes + 1.0)) / math.log(V + 1.0)
    return nd.log(-nd.expm1(num_tries * nd.log1p(-p)) + 1e-30)


def sampled_softmax_loss(h, labels, dec_w, dec_b, num_samples):
    """h (N, H); labels (N,). Scores 1 true + S shared negatives with
    importance correction; removes accidental hits."""
    samples = nd._sample_unique_zipfian(range_max=V,
                                        shape=(num_samples,))
    w_true = nd.take(dec_w, labels)                   # (N, H)
    b_true = nd.take(dec_b, labels)
    logit_true = (h * w_true).sum(axis=1) + b_true \
        - log_expected_count(labels.astype("float32"), num_samples)
    w_s = nd.take(dec_w, samples)                     # (S, H)
    b_s = nd.take(dec_b, samples)
    logit_s = nd.dot(h, w_s.T) + b_s.reshape((1, -1)) \
        - log_expected_count(samples.astype("float32"),
                             num_samples).reshape((1, -1))
    # accidental hits: a negative equal to the row's true class
    hit = (samples.reshape((1, -1)) ==
           labels.reshape((-1, 1))).astype("float32")
    logit_s = logit_s - 1e9 * hit
    logits = nd.concat(logit_true.reshape((-1, 1)), logit_s, dim=1)
    # true class sits at column 0
    return (nd.log(nd.exp(logits - logits.max(axis=1, keepdims=True))
                   .sum(axis=1))
            + logits.max(axis=1) - logits[:, 0]).mean()


def full_ppl(model, data, bptt, batch_size, ctx):
    states = model.begin_state(batch_size, ctx)
    dec_w, dec_b = model.dec_w.data(), model.dec_b.data()
    total, n = 0.0, 0
    for i in range(0, data.shape[0] - 1 - bptt, bptt):
        x = nd.array(data[i:i + bptt])
        y = nd.array(data[i + 1:i + 1 + bptt]).reshape((-1,))
        out, states = model(x, states)
        h = out.reshape((-1, out.shape[-1]))
        logits = nd.dot(h, dec_w.T) + dec_b.reshape((1, -1))
        lse = nd.log(nd.exp(logits - logits.max(axis=1, keepdims=True))
                     .sum(axis=1)) + logits.max(axis=1)
        picked = nd.pick(logits, y, axis=1)
        total += float((lse - picked).mean().asnumpy())
        n += 1
    return math.exp(total / max(n, 1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--emsize", type=int, default=64)
    ap.add_argument("--nhid", type=int, default=64)
    ap.add_argument("--num-samples", type=int, default=256)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--batch_size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--seed", type=int, default=3)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    ctx = mx.cpu()

    stream = make_corpus(rng)
    nb = len(stream) // args.batch_size
    data = stream[:nb * args.batch_size].reshape(args.batch_size, nb).T
    n_train = int(data.shape[0] * 0.9)
    train, valid = data[:n_train], data[n_train:]

    model = RNNLM(args.emsize, args.nhid)
    model.initialize(ctx=ctx)
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr})

    for epoch in range(args.epochs):
        states = model.begin_state(args.batch_size, ctx)
        lsum, n = 0.0, 0
        for i in range(0, train.shape[0] - 1 - args.bptt, args.bptt):
            x = nd.array(train[i:i + args.bptt])
            y = nd.array(train[i + 1:i + 1 + args.bptt]).reshape((-1,))
            states = [s.detach() for s in states]
            with autograd.record():
                out, states = model(x, states)
                h = out.reshape((-1, out.shape[-1]))
                loss = sampled_softmax_loss(
                    h, y, model.dec_w.data(), model.dec_b.data(),
                    args.num_samples)
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
            n += 1
        ppl = full_ppl(model, valid, args.bptt, args.batch_size, ctx)
        logging.info("epoch %d sampled loss %.3f full valid ppl %.1f "
                     "(uniform=%d)", epoch, lsum / n, ppl, V)
    print("FINAL_VALID_PPL %.2f" % ppl)


if __name__ == "__main__":
    main()
