"""Character-level CNN text classification — the reference's
`example/cnn_chinese_text_classification/` role: classification over
character-id sequences (no word segmentation, the point of the
char-level approach for Chinese), multi-width parallel convolutions +
max-over-time pooling (Kim 2014 applied to chars).

Synthetic task: 3 "topics", each with its own set of high-frequency
character bigrams embedded in noise — only local n-gram detectors (the
conv filters) can solve it.

Run:  python char_cnn.py [--epochs 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

VOCAB = 400        # "characters"
N_CLASS = 3
SEQ_LEN = 40
# class-specific character bigrams (like topical hanzi pairs)
TOPIC_BIGRAMS = {0: [(10, 11), (12, 13), (14, 15)],
                 1: [(20, 21), (22, 23), (24, 25)],
                 2: [(30, 31), (32, 33), (34, 35)]}


def make_batch(rng, n):
    xs = rng.randint(50, VOCAB, (n, SEQ_LEN))
    ys = rng.randint(0, N_CLASS, n)
    for i in range(n):
        for _ in range(rng.randint(3, 6)):
            a, b = TOPIC_BIGRAMS[ys[i]][rng.randint(0, 3)]
            p = rng.randint(0, SEQ_LEN - 1)
            xs[i, p], xs[i, p + 1] = a, b
    return xs.astype(np.float32), ys.astype(np.float32)


class CharCNN(gluon.nn.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.emb = gluon.nn.Embedding(VOCAB, 24)
            self.convs = [gluon.nn.Conv1D(24, k, prefix="conv%d_" % k)
                          for k in (2, 3, 4)]
            for c in self.convs:
                self.register_child(c)
            self.out = gluon.nn.Dense(N_CLASS)
            self.drop = gluon.nn.Dropout(0.3)

    def hybrid_forward(self, F, x):
        e = self.emb(x).transpose((0, 2, 1))   # (B, emb, T)
        pooled = [nd.relu(c(e)).max(axis=2) for c in self.convs]
        return self.out(self.drop(nd.concat(*pooled, dim=1)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=6)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    net = CharCNN()
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        lsum = 0.0
        for _ in range(15):
            x, y = make_batch(rng, args.batch_size)
            with autograd.record():
                loss = loss_fn(net(nd.array(x)), nd.array(y)).mean()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
        x, y = make_batch(rng, 128)
        acc = float((net(nd.array(x)).asnumpy().argmax(1) == y).mean())
        logging.info("epoch %d loss %.4f accuracy %.3f", epoch,
                     lsum / 15, acc)
    print("FINAL_ACCURACY %.4f" % acc)


if __name__ == "__main__":
    main()
