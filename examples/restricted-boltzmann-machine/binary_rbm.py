"""Binary restricted Boltzmann machine — the reference's
`example/restricted-boltzmann-machine/` role: CD-k contrastive
divergence on Bernoulli visible/hidden units, free-energy gap
monitoring, and reconstruction error.  TPU-first: a CD step is three
matmuls + Bernoulli sampling via the framework's counter-based RNG —
no per-unit loops.

Synthetic data: 4 prototype 6x6 binary patterns with flip noise; the
RBM must carve energy wells around the prototypes.

Run:  python binary_rbm.py [--epochs 15]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import nd

NV = 36      # 6x6 visible units
NH = 24


def make_protos(rng):
    protos = np.zeros((4, 6, 6), np.float32)
    protos[0, :3, :] = 1          # top half
    protos[1, :, :3] = 1          # left half
    protos[2][np.arange(6), np.arange(6)] = 1
    protos[2][np.arange(5), np.arange(1, 6)] = 1
    protos[3, 1:5, 1:5] = 1       # center block
    return protos.reshape(4, NV)


def make_batch(rng, protos, n):
    idx = rng.randint(0, len(protos), n)
    v = protos[idx].copy()
    flip = rng.rand(n, NV) < 0.05
    v[flip] = 1 - v[flip]
    return v.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--cd-k", type=int, default=1)
    ap.add_argument("--seed", type=int, default=2)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    protos = make_protos(rng)

    W = nd.random.normal(0, 0.05, (NV, NH))
    bv = nd.zeros((NV,))
    bh = nd.zeros((NH,))

    def sample(p):
        return (nd.random.uniform(0, 1, p.shape) < p) * 1.0

    def hprob(v):
        return nd.sigmoid(nd.dot(v, W) + bh)

    def vprob(h):
        return nd.sigmoid(nd.dot(h, W.T) + bv)

    for epoch in range(args.epochs):
        err = 0.0
        for _ in range(20):
            v0 = nd.array(make_batch(rng, protos, args.batch_size))
            ph0 = hprob(v0)
            h = sample(ph0)
            for _k in range(args.cd_k):          # CD-k Gibbs chain
                v = sample(vprob(h))
                ph = hprob(v)
                h = sample(ph)
            n = v0.shape[0]
            W += args.lr * (nd.dot(v0.T, ph0) - nd.dot(v.T, ph)) / n
            bv += args.lr * (v0 - v).mean(axis=0)
            bh += args.lr * (ph0 - ph).mean(axis=0)
            err += float(((v0 - vprob(hprob(v0))) ** 2).mean().asnumpy())
        recon = err / 20
        # free-energy gap between data and noise: should grow
        vd = nd.array(make_batch(rng, protos, 64))
        vn = nd.array((rng.rand(64, NV) < 0.5).astype(np.float32))

        def free_energy(v):
            return (- nd.dot(v, bv.reshape((-1, 1))).reshape((-1,))
                    - nd.log(1 + nd.exp(nd.dot(v, W) + bh)).sum(axis=1))

        gap = float((free_energy(vn).mean() -
                     free_energy(vd).mean()).asnumpy())
        logging.info("epoch %d reconstruction error %.4f "
                     "free-energy gap %.2f", epoch, recon, gap)
    print("FINAL_RECON_ERROR %.4f" % recon)


if __name__ == "__main__":
    main()
