"""Fast Gradient Sign Method adversarial examples.

Analog of the reference's `example/adversary/adversary_generation.ipynb`:
train a small convnet, then perturb inputs along the sign of the INPUT
gradient and watch accuracy collapse.  Exercises gluon training plus
`autograd` input gradients (`x.attach_grad()` on data, not parameters)
— on TPU the attack step is one fused XLA program per batch.

Run:  python fgsm_mnist.py [--epochs 3] [--epsilon 0.15]
Synthetic data by default (no egress); point --mnist-dir at raw MNIST
ubyte files to use real digits.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


def build_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(10))
    return net


def get_data(args):
    if args.mnist_dir and os.path.exists(
            os.path.join(args.mnist_dir, "train-images-idx3-ubyte")):
        it = mx.io.MNISTIter(
            image=os.path.join(args.mnist_dir, "train-images-idx3-ubyte"),
            label=os.path.join(args.mnist_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True)
        return it
    logging.info("using synthetic class-template digits")
    rng = np.random.RandomState(0)
    templates = rng.uniform(0, 1, (10, 1, 28, 28)).astype(np.float32)
    y = rng.randint(0, 10, (1024,))
    x = templates[y] + rng.normal(0, 0.08, (1024, 1, 28, 28)) \
        .astype(np.float32)
    return mx.io.NDArrayIter(x.astype(np.float32),
                             y.astype(np.float32),
                             batch_size=args.batch_size, shuffle=True)


def evaluate(net, it, ctx, epsilon, loss_fn):
    """Accuracy on clean and FGSM-perturbed inputs."""
    clean = mx.metric.Accuracy()
    adv = mx.metric.Accuracy()
    it.reset()
    for batch in it:
        x = batch.data[0].as_in_context(ctx)
        y = batch.label[0].as_in_context(ctx)
        clean.update([y], [net(x)])
        x.attach_grad()
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        # the attack: one signed step along the input gradient
        x_adv = x + epsilon * x.grad.sign()
        adv.update([y], [net(x_adv)])
    return clean.get()[1], adv.get()[1]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epsilon", type=float, default=0.15)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--mnist-dir", default=None)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = build_net()
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    it = get_data(args)
    for epoch in range(args.epochs):
        it.reset()
        total = n = 0.0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].as_in_context(ctx)
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            trainer.step(x.shape[0])
            total += float(loss.mean().asnumpy())
            n += 1
        logging.info("epoch %d loss %.4f", epoch, total / n)

    clean_acc, adv_acc = evaluate(net, it, ctx, args.epsilon, loss_fn)
    logging.info("clean accuracy:        %.3f", clean_acc)
    logging.info("FGSM(eps=%.2f) accuracy: %.3f", args.epsilon, adv_acc)
    assert adv_acc < clean_acc, "the attack should reduce accuracy"


if __name__ == "__main__":
    main()
