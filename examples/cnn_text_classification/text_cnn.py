"""CNN for sentence classification (Kim 2014).

Analog of the reference's `example/cnn_text_classification/text_cnn.py`:
token ids -> Embedding -> parallel Conv1D banks with widths (3, 4, 5)
-> global max pool -> concat -> Dense.  Builds its Vocabulary with
`mxtpu.contrib.text` and embeds with a CustomEmbedding when
--embedding-file is given.

Run:  python text_cnn.py [--epochs 6]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging
from collections import Counter

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.contrib import text as ctext

POS_WORDS = "good great fine excellent love nice happy best".split()
NEG_WORDS = "bad awful poor terrible hate sad angry worst".split()
FILLER = "the a an it this movie film was is very so and".split()


def make_corpus(n=512, seq_len=8, seed=0):
    rng = np.random.RandomState(seed)
    sents, labels = [], []
    for _ in range(n):
        y = rng.randint(2)
        pool = POS_WORDS if y else NEG_WORDS
        words = [rng.choice(pool) if rng.rand() < 0.4
                 else rng.choice(FILLER) for _ in range(seq_len)]
        if not any(w in pool for w in words):
            words[rng.randint(seq_len)] = rng.choice(pool)
        sents.append(words)
        labels.append(y)
    return sents, np.asarray(labels, np.float32)


class TextCNN(gluon.nn.HybridBlock):
    def __init__(self, vocab_size, embed_dim=32, num_filter=16,
                 widths=(3, 4, 5), num_classes=2):
        super().__init__()
        self.embed = gluon.nn.Embedding(vocab_size, embed_dim)
        self.convs = []
        for i, w in enumerate(widths):
            conv = gluon.nn.Conv1D(num_filter, w, activation="relu")
            setattr(self, "conv%d" % i, conv)
            self.convs.append(conv)
        self.pool = gluon.nn.GlobalMaxPool1D()
        self.out = gluon.nn.Dense(num_classes)

    def hybrid_forward(self, F, x):
        e = self.embed(x)                  # (N, T, E)
        e = F.transpose(e, axes=(0, 2, 1))  # Conv1D wants NCW
        feats = [F.Flatten(self.pool(c(e))) for c in self.convs]
        return self.out(F.concat(*feats, dim=1))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=6)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--embedding-file", default=None,
                   help="optional pretrained vectors (token v1 v2 ...)")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    sents, labels = make_corpus()
    counter = Counter(w for s in sents for w in s)
    vocab = ctext.Vocabulary(counter, reserved_tokens=["<pad>"])
    X = np.asarray([vocab.to_indices(s) for s in sents], np.float32)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = TextCNN(len(vocab))
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    if args.embedding_file:
        emb = ctext.embedding.CustomEmbedding(args.embedding_file,
                                              counter=counter)
        net.embed.weight.set_data(emb.idx_to_vec)
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    it = mx.io.NDArrayIter(X, labels, batch_size=args.batch_size,
                           shuffle=True)
    for epoch in range(args.epochs):
        it.reset()
        metric = mx.metric.Accuracy()
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].as_in_context(ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(x.shape[0])
            metric.update([y], [out])
        logging.info("epoch %d train accuracy %.3f", epoch,
                     metric.get()[1])
    assert metric.get()[1] > 0.9, "sentiment CNN should fit the corpus"


if __name__ == "__main__":
    main()
