"""Bayesian methods — the reference's `example/bayesian-methods/` role
(SGLD, Welling & Teh 2011): stochastic-gradient Langevin dynamics over
a Bayesian logistic-regression posterior, with a polynomially-decaying
step size, burn-in, posterior-sample collection, and predictive
ensembling vs the plain SGD point estimate.

Run:  python sgld_logistic.py [--iters 1500]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, nd

DIM = 8


def make_data(rng, n):
    w_true = rng.randn(DIM) * 2
    X = rng.randn(n, DIM).astype(np.float32)
    p = 1 / (1 + np.exp(-(X @ w_true)))
    y = (rng.rand(n) < p).astype(np.float32)
    return X, y, w_true


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=1500)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--burn-in", type=int, default=500)
    ap.add_argument("--n-train", type=int, default=600)
    ap.add_argument("--prior-prec", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=13)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X, y, w_true = make_data(rng, args.n_train + 400)
    Xtr, ytr = X[:args.n_train], y[:args.n_train]
    Xte, yte = X[args.n_train:], y[args.n_train:]
    n = len(Xtr)

    w = nd.zeros((DIM,))
    w.attach_grad()
    samples = []
    for t in range(args.iters):
        # Welling&Teh schedule: eps_t = a (b + t)^-gamma
        eps = 0.4 * (10 + t) ** (-0.55)
        idx = rng.randint(0, n, args.batch_size)
        xb, yb = nd.array(Xtr[idx]), nd.array(ytr[idx])
        with autograd.record():
            logit = nd.dot(xb, w.reshape((-1, 1))).reshape((-1,))
            # negative log joint (scaled to the full dataset)
            nll = (nd.relu(logit) - logit * yb +
                   nd.log(1 + nd.exp(-nd.abs(logit)))).sum() \
                * (n / args.batch_size)
            neg_log_joint = nll + 0.5 * args.prior_prec * (w ** 2).sum()
        neg_log_joint.backward()
        noise = nd.random.normal(0, float(np.sqrt(eps)), (DIM,))
        w -= 0.5 * eps * w.grad
        w += noise
        if t >= args.burn_in and t % 10 == 0:
            samples.append(w.asnumpy().copy())
        if (t + 1) % 300 == 0:
            logging.info("iter %d eps %.2e kept %d samples", t + 1,
                         eps, len(samples))

    S = np.stack(samples)               # (S, DIM) posterior samples
    # posterior-predictive ensemble vs the last-iterate point estimate
    def acc(wv):
        return float((((Xte @ wv) > 0) == yte).mean())

    p_ens = np.mean(1 / (1 + np.exp(-(Xte @ S.T))), axis=1)
    ens_acc = float(((p_ens > 0.5) == yte).mean())
    point_acc = acc(w.asnumpy())
    post_std = S.std(axis=0).mean()
    logging.info("posterior mean |w - w_true| = %.3f, mean std %.3f",
                 float(np.abs(S.mean(0) - w_true).mean()), post_std)
    logging.info("point accuracy %.3f ensemble accuracy %.3f",
                 point_acc, ens_acc)
    print("FINAL_ENSEMBLE_ACCURACY %.4f" % ens_acc)


if __name__ == "__main__":
    main()
