"""Example-script utilities (reference `common/util.py`)."""
import os
import re


def apply_platform_env():
    """Honor JAX_PLATFORMS / xla_force_host_platform_device_count even
    though the interpreter may have imported jax before this script ran
    (the env vars are captured at import): re-assert them through the
    config API, which works any time before backend init.  Same trick
    as tests/conftest.py."""
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    jax.config.update("jax_platforms", platforms)
    m = re.search(r"xla_force_host_platform_device_count=(\d+)",
                  os.environ.get("XLA_FLAGS", ""))
    if m and "cpu" in platforms:
        try:
            jax.config.update("jax_num_cpu_devices", int(m.group(1)))
        except Exception:
            pass
