"""Training harness for the image-classification examples (reference
`example/image-classification/common/fit.py`): arg surface, lr schedule,
checkpoint/resume, monitor, and the Module.fit call.

TPU-first differences from the reference:
  * devices come from the jax platform (all local TPU chips, or the
    virtual CPU mesh in tests) instead of a --gpus list;
  * --dtype bfloat16/float16 enables the AMP compute policy
    (`mxtpu/amp.py`) — fp32 master weights, low-precision matmuls — not
    a symbol-level cast;
  * --kv-store tpu rides the XLA allreduce path (BASELINE.json north
    star).
"""
import logging
import math
import os
import re
import time


def get_epoch_size(args, kv):
    return math.ceil(int(args.num_examples / kv.num_workers)
                     / args.batch_size)


def _get_lr_scheduler(args, kv):
    import mxtpu as mx

    if not args.lr_step_epochs or args.lr_factor >= 1:
        return (args.lr, None)
    epoch_size = get_epoch_size(args, kv)
    begin_epoch = args.load_epoch or 0
    if "pow" in args.lr_step_epochs:
        pwr = float(re.sub(r"pow[- ]*", "", args.lr_step_epochs))
        max_up = args.num_epochs * epoch_size
        return (args.lr, mx.lr_scheduler.PolyScheduler(
            max_up, base_lr=args.lr, pwr=pwr))
    step_epochs = [int(x) for x in args.lr_step_epochs.split(",")]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d",
                     lr, begin_epoch)
    steps = [epoch_size * (x - begin_epoch) for x in step_epochs
             if x - begin_epoch > 0]
    if not steps:
        return (lr, None)
    return (lr, mx.lr_scheduler.MultiFactorScheduler(
        step=steps, factor=args.lr_factor, base_lr=args.lr))


def _load_model(args, rank=0):
    import mxtpu as mx

    if args.load_epoch is None or args.model_prefix is None:
        return (None, None, None)
    model_prefix = args.model_prefix
    if rank > 0 and os.path.exists("%s-%d-symbol.json"
                                   % (model_prefix, rank)):
        model_prefix += "-%d" % rank
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        model_prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", model_prefix,
                 args.load_epoch)
    return (sym, arg_params, aux_params)


def _save_model(args, rank=0):
    import mxtpu as mx

    if args.model_prefix is None:
        return None
    return mx.callback.do_checkpoint(
        args.model_prefix if rank == 0 else "%s-%d"
        % (args.model_prefix, rank),
        period=args.save_period)


def add_fit_args(parser):
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str,
                       help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers, for nets like resnet")
    train.add_argument("--num-devices", type=int, default=0,
                       help="devices to train on; 0 = all visible")
    train.add_argument("--kv-store", type=str, default="tpu",
                       help="key-value store type (tpu = XLA allreduce)")
    train.add_argument("--num-epochs", type=int, default=100)
    train.add_argument("--lr", type=float, default=0.1)
    train.add_argument("--lr-factor", type=float, default=0.1)
    train.add_argument("--lr-step-epochs", type=str,
                       help="epochs to reduce the lr, e.g. 30,60")
    train.add_argument("--initializer", type=str, default="default")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=0.0001)
    train.add_argument("--batch-size", type=int, default=128,
                       help="GLOBAL batch size (split over devices)")
    train.add_argument("--disp-batches", type=int, default=20)
    train.add_argument("--model-prefix", type=str)
    train.add_argument("--save-period", type=int, default=1)
    train.add_argument("--monitor", type=int, default=0)
    train.add_argument("--load-epoch", type=int)
    train.add_argument("--top-k", type=int, default=0)
    train.add_argument("--dtype", type=str, default="float32",
                       choices=("float32", "bfloat16", "float16"),
                       help="compute precision (AMP for bf16/fp16)")
    train.add_argument("--max-batches", type=int, default=0,
                       help="stop each epoch after N batches (smoke runs)")
    return train


def _devices(args):
    import mxtpu as mx

    n = mx.num_tpus()
    if n:
        devs = [mx.tpu(i) for i in range(n)]
    else:
        import jax

        devs = [mx.cpu(i) for i in range(len(jax.devices()))]
    if args.num_devices:
        devs = devs[:args.num_devices]
    return devs


def _initializer(args):
    import mxtpu as mx

    if args.initializer in ("default", "xavier"):
        return mx.initializer.Xavier(rnd_type="gaussian",
                                     factor_type="in", magnitude=2)
    if args.initializer == "msra":
        return mx.initializer.MSRAPrelu()
    return mx.initializer.Uniform(0.01)


def fit(args, network, data_loader_fn, **kwargs):
    """Train `network` (a Symbol) with the data from `data_loader_fn`
    (reference `common/fit.py fit`)."""
    import mxtpu as mx

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")
    logging.info("start with arguments %s", args)

    if args.dtype != "float32":
        mx.amp.set_compute_dtype(args.dtype)

    kv = mx.kv.create(args.kv_store)
    train, val = data_loader_fn(args, kv)

    epoch_size = get_epoch_size(args, kv)
    lr, lr_scheduler = _get_lr_scheduler(args, kv)
    sym, arg_params, aux_params = _load_model(args, kv.rank)
    if sym is not None:
        network = sym
    checkpoint = _save_model(args, kv.rank)

    devs = _devices(args)
    logging.info("devices: %s", devs)
    mod = mx.mod.Module(network, context=devs,
                        data_names=[d.name for d in train.provide_data],
                        label_names=[l.name for l in train.provide_label])

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler,
        "multi_precision": args.dtype != "float32",
    }
    if args.optimizer in ("sgd", "nag", "signum", "lbsgd"):
        optimizer_params["momentum"] = args.mom

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]
    monitor = mx.monitor.Monitor(args.monitor, pattern=".*") \
        if args.monitor > 0 else None

    if args.max_batches:
        train = _TruncatedIter(train, args.max_batches)

    mod.fit(train,
            begin_epoch=args.load_epoch or 0,
            num_epoch=args.num_epochs,
            eval_data=val,
            eval_metric=eval_metrics,
            kvstore=kv,
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            initializer=_initializer(args),
            arg_params=arg_params,
            aux_params=aux_params,
            batch_end_callback=batch_end_callbacks,
            epoch_end_callback=checkpoint,
            allow_missing=True,
            monitor=monitor)
    return mod


class _TruncatedIter(object):
    """Cap an iterator at N batches/epoch (smoke-testing aid)."""

    def __init__(self, base, max_batches):
        self._base = base
        self._max = max_batches
        self._n = 0
        self.provide_data = base.provide_data
        self.provide_label = base.provide_label
        self.batch_size = base.batch_size

    def __iter__(self):
        return self

    def next(self):
        if self._n >= self._max:
            raise StopIteration
        self._n += 1
        return next(self._base)

    __next__ = next

    def reset(self):
        self._n = 0
        self._base.reset()
