"""Data plumbing for the image-classification examples (reference
`example/image-classification/common/data.py`): recordio iterators with
worker sharding, standard augmentation flags, and a synthetic iterator
for hermetic benchmarking (`--benchmark 1`)."""
import os

import numpy as np


def add_data_args(parser):
    data = parser.add_argument_group("Data", "the input images")
    data.add_argument("--data-train", type=str, help="training record file")
    data.add_argument("--data-train-idx", type=str, default="",
                      help="training record index file")
    data.add_argument("--data-val", type=str, help="validation record file")
    data.add_argument("--data-val-idx", type=str, default="",
                      help="validation record index file")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939")
    data.add_argument("--rgb-std", type=str, default="1,1,1")
    data.add_argument("--pad-size", type=int, default=0)
    data.add_argument("--image-shape", type=str, default="3,224,224")
    data.add_argument("--num-classes", type=int, default=1000)
    data.add_argument("--num-examples", type=int, default=1281167)
    data.add_argument("--data-nthreads", type=int, default=4,
                      help="number of decode threads")
    data.add_argument("--benchmark", type=int, default=0,
                      help="if 1, run on synthetic data of --image-shape")
    return data


def add_data_aug_args(parser):
    aug = parser.add_argument_group("MXTPU data augmentations")
    aug.add_argument("--random-crop", type=int, default=0)
    aug.add_argument("--random-mirror", type=int, default=1)
    aug.add_argument("--max-random-h", type=int, default=0)
    aug.add_argument("--max-random-s", type=int, default=0)
    aug.add_argument("--max-random-l", type=int, default=0)
    aug.add_argument("--max-random-aspect-ratio", type=float, default=0)
    aug.add_argument("--max-random-rotate-angle", type=int, default=0)
    aug.add_argument("--max-random-shear-ratio", type=float, default=0)
    aug.add_argument("--max-random-scale", type=float, default=1)
    aug.add_argument("--min-random-scale", type=float, default=1)
    return aug


class SyntheticDataIter(object):
    """Fixed random batch served `epoch_size` times per epoch — the
    reference's `--benchmark 1` mode (`common/data.py SyntheticDataIter`):
    measures compute, not IO."""

    def __init__(self, num_classes, data_shape, epoch_size,
                 label_name="softmax_label", data_name="data"):
        from mxtpu import nd
        from mxtpu.io.io import DataDesc

        self.batch_size = data_shape[0]
        self.epoch_size = epoch_size
        self.cur_iter = 0
        rng = np.random.RandomState(0)
        self._data = nd.array(
            rng.uniform(-1, 1, data_shape).astype(np.float32))
        self._label = nd.array(
            rng.randint(0, num_classes, (self.batch_size,))
            .astype(np.float32))
        self.provide_data = [DataDesc(data_name, data_shape, np.float32)]
        self.provide_label = [DataDesc(label_name, (self.batch_size,),
                                       np.float32)]

    def __iter__(self):
        return self

    def next(self):
        from mxtpu.io.io import DataBatch

        if self.cur_iter >= self.epoch_size:
            raise StopIteration
        self.cur_iter += 1
        return DataBatch(data=[self._data], label=[self._label],
                         pad=0, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    __next__ = next

    def reset(self):
        self.cur_iter = 0


def get_rec_iter(args, kv=None):
    """(train, val) iterators; recordio-backed with rank sharding when
    --data-train is given, synthetic otherwise."""
    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    batch = args.batch_size
    if args.benchmark or not args.data_train:
        epoch_size = max(1, args.num_examples // batch)
        train = SyntheticDataIter(args.num_classes, (batch,) + image_shape,
                                  epoch_size)
        return train, None
    from mxtpu.io.record_iter import ImageRecordIter

    rank, nworker = (kv.rank, kv.num_workers) if kv else (0, 1)
    mean = [float(x) for x in args.rgb_mean.split(",")]
    std = [float(x) for x in args.rgb_std.split(",")]
    train = ImageRecordIter(
        path_imgrec=args.data_train,
        path_imgidx=args.data_train_idx,
        data_shape=image_shape,
        batch_size=batch,
        shuffle=True,
        rand_crop=bool(args.random_crop),
        rand_mirror=bool(args.random_mirror),
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        std_r=std[0], std_g=std[1], std_b=std[2],
        preprocess_threads=args.data_nthreads,
        num_parts=nworker, part_index=rank)
    val = None
    if args.data_val:
        val = ImageRecordIter(
            path_imgrec=args.data_val,
            path_imgidx=args.data_val_idx,
            data_shape=image_shape,
            batch_size=batch,
            shuffle=False,
            mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
            std_r=std[0], std_g=std[1], std_b=std[2],
            preprocess_threads=args.data_nthreads,
            num_parts=nworker, part_index=rank)
    return train, val
