"""Network symbols for the image-classification examples.

The reference ships hand-written symbol builders per network
(`example/image-classification/symbols/resnet.py` etc.).  The TPU-native
framework already has every architecture in the Gluon model zoo
(`mxtpu/gluon/model_zoo/vision`), so instead of duplicating the layer
stacks this module TRACES a zoo network into a Symbol — the same
hybridize machinery that powers `net.export()` — and attaches the
softmax head.  One definition per architecture, two frontends.
"""
import sys


def get_symbol(network="resnet", num_layers=50, num_classes=1000,
               image_shape=(3, 224, 224), **kwargs):
    """Build `network` from the gluon model zoo and trace it into a
    Symbol whose input is named "data" with a SoftmaxOutput head named
    "softmax" (reference `symbols/<net>.py get_symbol`)."""
    import mxtpu as mx
    from mxtpu import sym
    from mxtpu.gluon.model_zoo import vision

    if network in ("resnet", "resnet-v1"):
        net = vision.get_resnet(1, num_layers, classes=num_classes)
    elif network == "resnet-v2":
        net = vision.get_resnet(2, num_layers, classes=num_classes)
    elif network == "alexnet":
        net = vision.alexnet(classes=num_classes)
    elif network == "vgg":
        net = vision.get_vgg(num_layers or 16, classes=num_classes)
    elif network in ("inception-v3", "inception"):
        net = vision.inception_v3(classes=num_classes)
    elif network == "mobilenet":
        net = vision.mobilenet1_0(classes=num_classes)
    elif network == "squeezenet":
        net = vision.squeezenet1_0(classes=num_classes)
    elif network.startswith("densenet"):
        net = vision.densenet121(classes=num_classes)
    elif network in ("mlp", "lenet"):
        return _small_symbol(network, num_classes)
    else:
        raise ValueError("unknown network %r" % network)

    net.initialize()
    x_trace = mx.nd.zeros((1,) + tuple(image_shape))
    traced, _, _ = net._trace_symbol(x_trace)
    # the trace names its input data0 — compose to the conventional name
    out = traced(data0=sym.Variable("data"))
    return sym.SoftmaxOutput(data=out, label=sym.Variable("softmax_label"),
                             name="softmax")


def _small_symbol(network, num_classes):
    from mxtpu import sym

    data = sym.Variable("data")
    if network == "mlp":
        h = sym.FullyConnected(data=sym.Flatten(data), num_hidden=128,
                               name="fc1")
        h = sym.Activation(data=h, act_type="relu")
        h = sym.FullyConnected(data=h, num_hidden=num_classes, name="fc2")
    else:  # lenet
        h = sym.Convolution(data=data, kernel=(5, 5), num_filter=20,
                            name="conv1")
        h = sym.Activation(data=h, act_type="relu")
        h = sym.Pooling(data=h, kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
        h = sym.FullyConnected(data=sym.Flatten(h), num_hidden=num_classes,
                               name="fc")
    return sym.SoftmaxOutput(data=h, label=sym.Variable("softmax_label"),
                             name="softmax")
