"""Inference throughput across the model zoo.

Analog of the reference's
`example/image-classification/benchmark_score.py`: forward-only
images/sec for each zoo network at several batch sizes, via the
symbolic executor (one fused XLA program per (net, batch)).

Run:  python benchmark_score.py [--networks resnet18_v1,mobilenet1_0]
      [--batch-sizes 1,32] [--iters 20]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging
import time

import numpy as np

import mxtpu as mx
from mxtpu.gluon.model_zoo import vision


def score(name, batch, iters, ctx, dtype="float32", fused=0):
    """fused=K > 0 scores K batches per device program
    (HybridBlock.forward_fused) — on a remote-tunnel PJRT client the
    per-dispatch round trip otherwise dominates small-batch scoring."""
    amp_dtype = None if dtype == "float32" else dtype
    with mx.amp.scope(amp_dtype):
        net = getattr(vision, name)(classes=1000)
        net.initialize(ctx=ctx)
        x = mx.nd.array(np.random.uniform(size=(batch, 3, 224, 224))
                        .astype(np.float32), ctx=ctx)
        net(x)  # materialize deferred shapes
        net.hybridize()
        if fused:
            xs = mx.nd.array(np.random.uniform(
                size=(fused, batch, 3, 224, 224)).astype(np.float32),
                ctx=ctx)
            net.forward_fused(xs)[0].wait_to_read()  # compile
            tic = time.perf_counter()
            for _ in range(iters):
                out = net.forward_fused(xs)
            out[0].wait_to_read()
            dt = time.perf_counter() - tic
            return batch * fused * iters / dt
        net(x).wait_to_read()  # compile
        tic = time.perf_counter()
        for _ in range(iters):
            out = net(x)
        out.wait_to_read()
        dt = time.perf_counter() - tic
    return batch * iters / dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--networks",
                   default="resnet18_v1,resnet50_v1,mobilenet1_0")
    p.add_argument("--batch-sizes", default="1,32")
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--dtype", default="float32",
                   help="float32 or bfloat16 (AMP compute policy — the "
                        "TPU analog of the reference's fp16 scoring "
                        "rows, docs/faq/perf.md:166-176)")
    p.add_argument("--fused", type=int, default=0,
                   help="score K batches per device program "
                        "(amortizes remote dispatch latency)")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    logging.info("device: %s", ctx)
    for name in args.networks.split(","):
        for bs in (int(b) for b in args.batch_sizes.split(",")):
            ips = score(name.strip(), bs, args.iters, ctx,
                        dtype=args.dtype, fused=args.fused)
            logging.info("network %-16s batch %3d %s%s: %9.1f images/sec",
                         name, bs, args.dtype,
                         " fused=%d" % args.fused if args.fused else "",
                         ips)


if __name__ == "__main__":
    main()
