#!/usr/bin/env python
"""Train an MLP or LeNet on MNIST with the Module API.

The analog of the reference's `example/image-classification/train_mnist.py`
(BASELINE.json config #1): `Module.fit` over a symbolic network, kvstore
selectable (`--kv-store tpu` for the ICI allreduce path).

With --dummy (or when no MNIST files are present) synthetic data is used
so the script runs hermetically, like the reference's `--benchmark 1`.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import sym
from mxtpu.io.io import NDArrayIter


def mlp_symbol(num_classes=10):
    data = sym.Variable("data")
    data = sym.Flatten(data)
    fc1 = sym.FullyConnected(data=data, num_hidden=128, name="fc1")
    act1 = sym.Activation(data=fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(data=act1, num_hidden=64, name="fc2")
    act2 = sym.Activation(data=fc2, act_type="relu", name="relu2")
    fc3 = sym.FullyConnected(data=act2, num_hidden=num_classes, name="fc3")
    return sym.SoftmaxOutput(data=fc3, name="softmax",
                             label=sym.Variable("softmax_label"))


def lenet_symbol(num_classes=10):
    data = sym.Variable("data")
    c1 = sym.Convolution(data=data, kernel=(5, 5), num_filter=20,
                         name="conv1")
    a1 = sym.Activation(data=c1, act_type="tanh", name="tanh1")
    p1 = sym.Pooling(data=a1, pool_type="max", kernel=(2, 2), stride=(2, 2),
                     name="pool1")
    c2 = sym.Convolution(data=p1, kernel=(5, 5), num_filter=50,
                         name="conv2")
    a2 = sym.Activation(data=c2, act_type="tanh", name="tanh2")
    p2 = sym.Pooling(data=a2, pool_type="max", kernel=(2, 2), stride=(2, 2),
                     name="pool2")
    f = sym.Flatten(p2)
    fc1 = sym.FullyConnected(data=f, num_hidden=500, name="fc1")
    a3 = sym.Activation(data=fc1, act_type="tanh", name="tanh3")
    fc2 = sym.FullyConnected(data=a3, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(data=fc2, name="softmax",
                             label=sym.Variable("softmax_label"))


def get_iters(args, image_shape):
    mnist_dir = args.data_dir
    have_mnist = mnist_dir and os.path.exists(
        os.path.join(mnist_dir, "train-images-idx3-ubyte"))
    if args.dummy or not have_mnist:
        logging.info("using synthetic data")
        rng = np.random.RandomState(42)
        n = args.num_examples
        x = rng.rand(n, *image_shape).astype(np.float32)
        y = rng.randint(0, 10, n).astype(np.float32)
        split = int(n * 0.9)
        train = NDArrayIter(x[:split], y[:split], args.batch_size,
                            shuffle=True, label_name="softmax_label")
        val = NDArrayIter(x[split:], y[split:], args.batch_size,
                          label_name="softmax_label")
        return train, val
    from mxtpu.io.io import MNISTIter

    train = MNISTIter(
        image=os.path.join(mnist_dir, "train-images-idx3-ubyte"),
        label=os.path.join(mnist_dir, "train-labels-idx1-ubyte"),
        batch_size=args.batch_size, shuffle=True, flat=args.network == "mlp")
    val = MNISTIter(
        image=os.path.join(mnist_dir, "t10k-images-idx3-ubyte"),
        label=os.path.join(mnist_dir, "t10k-labels-idx1-ubyte"),
        batch_size=args.batch_size, flat=args.network == "mlp")
    return train, val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", choices=["mlp", "lenet"], default="mlp")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--kv-store", default="local")
    ap.add_argument("--num-examples", type=int, default=6000)
    ap.add_argument("--data-dir", default=os.environ.get("MNIST_DIR", ""))
    ap.add_argument("--dummy", action="store_true")
    ap.add_argument("--gpus", default="")  # parity flag; contexts below
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    np.random.seed(0)

    image_shape = (784,) if args.network == "mlp" else (1, 28, 28)
    net = mlp_symbol() if args.network == "mlp" else lenet_symbol()
    have_mnist = args.data_dir and os.path.exists(
        os.path.join(args.data_dir, "train-images-idx3-ubyte"))
    synthetic = args.dummy or not have_mnist
    train, val = get_iters(args, image_shape)

    ctx = [mx.tpu()] if mx.num_tpus() else [mx.cpu()]
    mod = mx.mod.Module(net, context=ctx)
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            kvstore=args.kv_store, num_epoch=args.num_epochs,
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50))
    acc = mod.score(val, "acc")[0][1]
    logging.info("final validation accuracy: %.4f", acc)
    return 0 if acc > (0.0 if synthetic else 0.9) else 1


if __name__ == "__main__":
    raise SystemExit(main())
