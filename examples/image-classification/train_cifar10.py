#!/usr/bin/env python
"""Train CIFAR-10 (reference `example/image-classification/train_cifar10.py`).

Same harness as train_imagenet.py at 32x32: ResNet-20-ish depth via the
model-zoo builders, synthetic fallback with --benchmark 1.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import data, fit, util
from symbols import zoo

util.apply_platform_env()

if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_data_aug_args(parser)
    parser.set_defaults(
        network="resnet",
        num_layers=18,
        num_classes=10,
        num_examples=50000,
        image_shape="3,32,32",
        batch_size=128,
        num_epochs=300,
        lr_step_epochs="50,100",
    )
    args = parser.parse_args()

    net = zoo.get_symbol(
        network=args.network, num_layers=args.num_layers,
        num_classes=args.num_classes,
        image_shape=tuple(int(x) for x in args.image_shape.split(",")))

    fit.fit(args, net, data.get_rec_iter)
