#!/usr/bin/env python
"""Train ImageNet-1k — the BASELINE.json north-star config
(reference `example/image-classification/train_imagenet.py:1-60`).

The default is ResNet-50 v1 with `--kv-store tpu`: data-parallel over
every visible chip with gradients merged by the XLA allreduce path.
Run hermetically with `--benchmark 1` (synthetic data), or point
--data-train/--data-val at recordio files packed by `tools/im2rec.py`.

Examples:
  # throughput smoke on whatever devices are visible
  python train_imagenet.py --benchmark 1 --num-epochs 1 --max-batches 30

  # bf16 AMP training, 8-way data parallel, checkpointing
  python train_imagenet.py --data-train train.rec --dtype bfloat16 \
      --model-prefix ckpt/resnet50
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import data, fit, util
from symbols import zoo

util.apply_platform_env()


def set_imagenet_aug(parser):
    """Standard ImageNet augmentation defaults (reference
    train_imagenet.py set_imagenet_aug)."""
    parser.set_defaults(rgb_mean="123.68,116.779,103.939",
                        rgb_std="58.393,57.12,57.375",
                        random_crop=1, random_mirror=1)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(
        description="train imagenet-1k",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_data_aug_args(parser)
    parser.set_defaults(
        network="resnet",
        num_layers=50,
        num_classes=1000,
        num_examples=1281167,
        image_shape="3,224,224",
        num_epochs=80,
        lr_step_epochs="30,60",
        dtype="float32",
    )
    args = parser.parse_args()

    net = zoo.get_symbol(
        network=args.network, num_layers=args.num_layers,
        num_classes=args.num_classes,
        image_shape=tuple(int(x) for x in args.image_shape.split(",")))

    fit.fit(args, net, data.get_rec_iter)
