"""VAE-GAN — the reference's `example/vae-gan/` role (Larsen et al.
2016): a VAE whose reconstruction loss is computed in the
DISCRIMINATOR's feature space instead of pixel space, trained jointly
with the GAN game: encoder minimizes KL + feature reconstruction,
decoder additionally fools the discriminator, discriminator separates
real / reconstructed / sampled.

Synthetic data: 16x16 images of axis-aligned bright blobs with varying
position/size — a 2-factor manifold the latent space must capture.

Run:  python vae_gan_mini.py [--epochs 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

IMG = 16
LATENT = 4


def make_batch(rng, n):
    xs = np.zeros((n, 1, IMG, IMG), np.float32)
    for i in range(n):
        cx, cy = rng.randint(3, IMG - 3, 2)
        s = rng.randint(2, 5)
        y0, y1 = max(cy - s, 0), min(cy + s, IMG)
        x0, x1 = max(cx - s, 0), min(cx + s, IMG)
        xs[i, 0, y0:y1, x0:x1] = 1.0
    xs += 0.05 * rng.randn(*xs.shape).astype(np.float32)
    return xs


def build_nets():
    enc = gluon.nn.HybridSequential(prefix="enc_")
    enc.add(gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                            activation="relu"),
            gluon.nn.Conv2D(32, 3, strides=2, padding=1,
                            activation="relu"),
            gluon.nn.Dense(2 * LATENT))
    dec = gluon.nn.HybridSequential(prefix="dec_")
    dec.add(gluon.nn.Dense(32 * 4 * 4, activation="relu"))
    dec.add(gluon.nn.HybridLambda(
        lambda F, x: x.reshape((-1, 32, 4, 4))))
    dec.add(gluon.nn.Conv2DTranspose(16, 4, strides=2, padding=1,
                                     activation="relu"),
            gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1))
    dis_feat = gluon.nn.HybridSequential(prefix="disf_")
    dis_feat.add(gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                                 activation="relu"),
                 gluon.nn.Conv2D(32, 3, strides=2, padding=1,
                                 activation="relu"),
                 gluon.nn.Dense(64, activation="relu"))
    dis_head = gluon.nn.Dense(1, prefix="dish_")
    return enc, dec, dis_feat, dis_head


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=19)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    enc, dec, dis_feat, dis_head = build_nets()
    for b in (enc, dec, dis_feat, dis_head):
        b.initialize(ctx=mx.cpu())
    vae_params = gluon.ParameterDict()
    vae_params.update(enc.collect_params())
    vae_params.update(dec.collect_params())
    dis_params = gluon.ParameterDict()
    dis_params.update(dis_feat.collect_params())
    dis_params.update(dis_head.collect_params())
    t_vae = gluon.Trainer(vae_params, "adam",
                          {"learning_rate": args.lr})
    t_dis = gluon.Trainer(dis_params, "adam",
                          {"learning_rate": args.lr})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    for epoch in range(args.epochs):
        dl_sum = gl_sum = 0.0
        for _ in range(20):
            x = nd.array(make_batch(rng, args.batch_size))
            B = x.shape[0]
            ones, zeros = nd.ones((B,)), nd.zeros((B,))
            # --- discriminator step: real vs recon + prior samples
            h = enc(x)
            mu, logv = h[:, :LATENT], h[:, LATENT:]
            z = mu + nd.exp(0.5 * logv) * nd.random.normal(
                0, 1, mu.shape)
            xr = dec(z).detach()
            zp = nd.random.normal(0, 1, mu.shape)
            xp = dec(zp).detach()
            with autograd.record():
                d_loss = (bce(dis_head(dis_feat(x)), ones) +
                          bce(dis_head(dis_feat(xr)), zeros) +
                          bce(dis_head(dis_feat(xp)), zeros)).mean()
            d_loss.backward()
            t_dis.step(1)
            # --- VAE step: KL + feature-space recon + fool the dis
            with autograd.record():
                h = enc(x)
                mu, logv = h[:, :LATENT], h[:, LATENT:]
                z = mu + nd.exp(0.5 * logv) * nd.random.normal(
                    0, 1, mu.shape)
                xr = dec(z)
                kl = (-0.5 * (1 + logv - mu ** 2 - nd.exp(logv))
                      .sum(axis=1)).mean()
                f_real = dis_feat(x).detach()
                f_rec = dis_feat(xr)
                recon = ((f_rec - f_real) ** 2).mean()
                fool = bce(dis_head(f_rec), ones).mean()
                g_loss = recon + 0.05 * kl + 0.1 * fool
            g_loss.backward()
            t_vae.step(1)
            dl_sum += float(d_loss.asnumpy())
            gl_sum += float(g_loss.asnumpy())
        # pixel recon as an external progress measure
        x = nd.array(make_batch(rng, 64))
        h = enc(x)
        xr = dec(h[:, :LATENT])
        pix = float(((xr - x) ** 2).mean().asnumpy())
        logging.info("epoch %d d_loss %.4f vae_loss %.4f pixel recon "
                     "%.4f", epoch, dl_sum / 20, gl_sum / 20, pix)
    print("FINAL_PIXEL_RECON %.4f" % pix)


if __name__ == "__main__":
    main()
