"""Kaggle NDSB-2 (Second Annual Data Science Bowl) — the reference's
`example/kaggle-ndsb2/` role: predict cardiac volume from an MRI
SEQUENCE (30 frames over the heart cycle) with a CNN frame encoder +
GRU over time + regression head, evaluated with the competition's CRPS
(continuous ranked probability score) over a step-function CDF.

Synthetic data: pulsing-disc "MRI" sequences whose radius oscillates;
the target volume is the max-phase disc area — recoverable only by
integrating over the sequence.

Run:  python heart_volume_rnn.py [--epochs 10]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

IMG = 16
T = 12           # frames per study
VMAX = 120       # volume bins for the CRPS CDF


def make_study(rng):
    base_r = rng.uniform(2.0, 5.5)
    amp = rng.uniform(0.5, 2.0)
    phase = rng.uniform(0, 2 * np.pi)
    yy, xx = np.mgrid[0:IMG, 0:IMG]
    c = IMG / 2.0
    frames = np.zeros((T, 1, IMG, IMG), np.float32)
    rmax = 0.0
    for t in range(T):
        r = base_r + amp * np.sin(2 * np.pi * t / T + phase)
        rmax = max(rmax, r)
        frames[t, 0] = (np.sqrt((yy - c) ** 2 + (xx - c) ** 2) < r)
    frames += 0.1 * rng.randn(T, 1, IMG, IMG).astype(np.float32)
    volume = np.pi * rmax ** 2   # "end-diastolic volume"
    return frames, np.float32(volume)


def make_batch(rng, n):
    xs, ys = zip(*[make_study(rng) for _ in range(n)])
    return np.stack(xs), np.array(ys, np.float32)


class HeartNet(gluon.nn.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.enc = gluon.nn.HybridSequential()
            self.enc.add(gluon.nn.Conv2D(8, 3, strides=2, padding=1,
                                         activation="relu"),
                         gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                                         activation="relu"),
                         gluon.nn.Dense(24, activation="relu"))
            self.gru = gluon.rnn.GRU(24)
            self.head = gluon.nn.Dense(1)

    def hybrid_forward(self, F, x):
        # x: (B, T, 1, H, W) -> encode frames -> GRU -> last state
        B, Tn = x.shape[0], x.shape[1]
        frames = x.reshape((-1, 1, IMG, IMG))
        feats = self.enc(frames).reshape((B, Tn, -1))
        h = self.gru(feats.transpose((1, 0, 2)))
        return self.head(h[-1]).reshape((-1,))


def crps(pred_vol, true_vol):
    """Competition metric: mean squared difference between the
    predicted step CDF H(v - pred) and the truth CDF H(v - true)."""
    v = np.arange(VMAX)[None, :]
    cdf_p = (v >= pred_vol[:, None]).astype(np.float32)
    cdf_t = (v >= true_vol[:, None]).astype(np.float32)
    return float(((cdf_p - cdf_t) ** 2).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=8)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    net = HeartNet()
    net.initialize(ctx=mx.cpu())
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})
    loss_fn = gluon.loss.HuberLoss(rho=1.0)
    SCALE = 50.0   # volumes span ~12-110: train in units of ~1

    Xv, yv = make_batch(rng, 64)
    naive = crps(np.full(64, yv.mean(), np.float32), yv)
    for epoch in range(args.epochs):
        lsum = 0.0
        for _ in range(12):
            x, y = make_batch(rng, args.batch_size)
            with autograd.record():
                loss = loss_fn(net(nd.array(x)),
                               nd.array(y / SCALE)).mean()
            loss.backward()
            tr.step(1)
            lsum += float(loss.asnumpy())
        pred = net(nd.array(Xv)).asnumpy() * SCALE
        score = crps(pred, yv)
        logging.info("epoch %d huber %.3f CRPS %.4f (predict-mean "
                     "baseline %.4f)", epoch, lsum / 12, score, naive)
    print("FINAL_CRPS %.4f" % score)


if __name__ == "__main__":
    main()
