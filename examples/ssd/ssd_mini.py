"""Single-shot detection, miniature.

Analog of the reference's `example/ssd/`: anchor priors
(`_contrib_MultiBoxPrior`), training-target assignment
(`_contrib_MultiBoxTarget`), joint class+box losses, and NMS decoding
(`_contrib_MultiBoxDetection`) — the full SSD op family end to end on a
synthetic one-object-per-image task.

Run:  python ssd_mini.py [--epochs 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

NUM_CLASSES = 2   # background + {square, cross}


class MiniSSD(gluon.nn.HybridBlock):
    """One feature map, one anchor scale set — the SSD skeleton."""

    def __init__(self, num_anchors):
        super().__init__()
        self.backbone = gluon.nn.HybridSequential()
        self.backbone.add(
            gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),                       # 16 -> 8
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2))                       # 8 -> 4
        self.cls_head = gluon.nn.Conv2D(num_anchors * (NUM_CLASSES + 1),
                                        3, padding=1)
        self.box_head = gluon.nn.Conv2D(num_anchors * 4, 3, padding=1)

    def hybrid_forward(self, F, x):
        feat = self.backbone(x)
        cls = F.transpose(self.cls_head(feat), axes=(0, 2, 3, 1))
        cls = F.Reshape(cls, shape=(0, -1, NUM_CLASSES + 1))
        box = F.transpose(self.box_head(feat), axes=(0, 2, 3, 1))
        box = F.Reshape(box, shape=(0, -1))
        return feat, cls, box


def make_data(n, seed=0):
    """Images with ONE object: class 1 = filled square, class 2 =
    cross; label rows are (cls, xmin, ymin, xmax, ymax) normalized."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, 1, 16, 16), np.float32)
    Y = np.zeros((n, 1, 5), np.float32)
    for i in range(n):
        c = rng.randint(1, NUM_CLASSES + 1)
        size = rng.randint(4, 7)
        r0 = rng.randint(0, 16 - size)
        c0 = rng.randint(0, 16 - size)
        if c == 1:
            X[i, 0, r0:r0 + size, c0:c0 + size] = 1.0
        else:
            X[i, 0, r0 + size // 2, c0:c0 + size] = 1.0
            X[i, 0, r0:r0 + size, c0 + size // 2] = 1.0
        Y[i, 0] = [c - 1, c0 / 16, r0 / 16, (c0 + size) / 16,
                   (r0 + size) / 16]
    return X, Y


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--det-threshold", type=float, default=0.2)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    sizes, ratios = (0.3, 0.5), (1.0, 2.0)
    num_anchors = len(sizes) + len(ratios) - 1
    net = MiniSSD(num_anchors)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    box_loss = gluon.loss.L1Loss()
    X, Y = make_data(256)
    it = mx.io.NDArrayIter(X, Y.reshape(len(Y), -1),
                           batch_size=args.batch_size, shuffle=True,
                           label_name="label")
    for epoch in range(args.epochs):
        it.reset()
        tot = n = 0.0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].reshape((-1, 1, 5)).as_in_context(ctx)
            with autograd.record():
                feat, cls_pred, box_pred = net(x)
                anchors = nd.contrib.MultiBoxPrior(
                    feat, sizes=sizes, ratios=ratios)
                # target assignment runs outside the gradient: it is a
                # matching procedure, not a differentiable op.  Hard
                # negative mining (3:1) keeps the overwhelming
                # background anchors from drowning the class loss —
                # mined-out anchors get ignore_label -1
                with autograd.pause():
                    box_t, box_mask, cls_t = nd.contrib.MultiBoxTarget(
                        anchors, y,
                        nd.transpose(cls_pred, axes=(0, 2, 1)),
                        negative_mining_ratio=3.0)
                logp = nd.log_softmax(cls_pred, axis=-1)
                keep = (cls_t >= 0).astype("float32")
                ce = -nd.pick(logp, nd.maximum(cls_t, 0.0), axis=2)
                l = (ce * keep).sum() / nd.maximum(keep.sum(), 1.0) + \
                    box_loss(box_pred * box_mask, box_t).mean()
            l.backward()
            trainer.step(x.shape[0])
            tot += float(l.mean().asnumpy())
            n += 1
        logging.info("epoch %d loss %.4f", epoch, tot / n)

    # decode: scores + offsets -> NMS'd detections
    x = nd.array(X[:8], ctx=ctx)
    feat, cls_pred, box_pred = net(x)
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=sizes, ratios=ratios)
    probs = nd.softmax(cls_pred, axis=-1)
    dets = nd.contrib.MultiBoxDetection(
        nd.transpose(probs, axes=(0, 2, 1)), box_pred, anchors,
        nms_threshold=0.45, threshold=args.det_threshold)
    d = dets.asnumpy()
    found = (d[:, :, 0] >= 0).sum(axis=1)
    logging.info("detections per image (first 8): %s", found.tolist())
    correct = 0
    for i in range(8):
        kept = d[i][d[i, :, 0] >= 0]
        if len(kept) and int(kept[0, 0]) == int(Y[i, 0, 0]):
            correct += 1
    logging.info("top-1 detection class correct: %d/8", correct)
    assert found.max() > 0, "should produce at least one detection"


if __name__ == "__main__":
    main()
