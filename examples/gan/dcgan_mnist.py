"""DCGAN on digit-shaped data.

Analog of the reference's `example/gan/dcgan.py`: transposed-conv
generator vs strided-conv discriminator, alternating SGD on the
non-saturating GAN objective.  Two gluon Trainers, label flipping, and
`autograd` over both networks — each D and G step compiles to one XLA
program on TPU.

Run:  python dcgan_mnist.py [--epochs 3] [--latent 32]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


def build_generator(latent):
    g = gluon.nn.HybridSequential()
    g.add(gluon.nn.Dense(64 * 7 * 7, activation="relu"),
          gluon.nn.HybridLambda(
              lambda F, x: F.Reshape(x, shape=(-1, 64, 7, 7))),
          gluon.nn.Conv2DTranspose(32, 4, strides=2, padding=1),
          gluon.nn.BatchNorm(), gluon.nn.Activation("relu"),
          gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                   activation="sigmoid"))
    return g


def build_discriminator():
    d = gluon.nn.HybridSequential()
    d.add(gluon.nn.Conv2D(32, 4, strides=2, padding=1),
          gluon.nn.LeakyReLU(0.2),
          gluon.nn.Conv2D(64, 4, strides=2, padding=1),
          gluon.nn.LeakyReLU(0.2),
          gluon.nn.Flatten(),
          gluon.nn.Dense(1))
    return d


def real_batches(n, batch, seed=0):
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[:28, :28]
    data = []
    for _ in range(n):
        imgs = np.zeros((batch, 1, 28, 28), np.float32)
        for i in range(batch):
            cx, cy, r = rng.randint(8, 20), rng.randint(8, 20), \
                rng.randint(4, 8)
            imgs[i, 0] = ((yy - cy) ** 2 + (xx - cx) ** 2 < r * r)
        data.append(imgs)
    return data


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batches-per-epoch", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--latent", type=int, default=32)
    p.add_argument("--lr", type=float, default=2e-4)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    gen, disc = build_generator(args.latent), build_discriminator()
    for net in (gen, disc):
        net.initialize(mx.initializer.Normal(0.02), ctx=ctx)
        net.hybridize()
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    ones = nd.ones((args.batch_size,), ctx=ctx)
    zeros = nd.zeros((args.batch_size,), ctx=ctx)
    data = real_batches(args.batches_per_epoch, args.batch_size)
    for epoch in range(args.epochs):
        d_loss_t = g_loss_t = 0.0
        for real_np in data:
            real = nd.array(real_np, ctx=ctx)
            z = mx.random.normal(0, 1, (args.batch_size, args.latent),
                                 ctx=ctx)
            # D step: real -> 1, fake -> 0 (fake detached by re-forward)
            fake = gen(z)
            with autograd.record():
                d_loss = loss_fn(disc(real), ones) + \
                    loss_fn(disc(fake), zeros)
            d_loss.backward()
            d_tr.step(args.batch_size)
            # G step: non-saturating, fool D towards 1
            with autograd.record():
                g_loss = loss_fn(disc(gen(z)), ones)
            g_loss.backward()
            g_tr.step(args.batch_size)
            d_loss_t += float(d_loss.mean().asnumpy())
            g_loss_t += float(g_loss.mean().asnumpy())
        n = len(data)
        logging.info("epoch %d  D loss %.4f  G loss %.4f", epoch,
                     d_loss_t / n, g_loss_t / n)
    sample = gen(mx.random.normal(0, 1, (4, args.latent), ctx=ctx))
    logging.info("generated sample range: [%.3f, %.3f]",
                 float(sample.min().asnumpy()),
                 float(sample.max().asnumpy()))
    assert sample.shape == (4, 1, 28, 28)


if __name__ == "__main__":
    main()
