"""Custom operator in Python (numpy-ops).

Analog of the reference's `example/numpy-ops/custom_softmax.py`: a
softmax-with-loss implemented as a `mx.operator.CustomOp` whose
forward/backward run HOST-side numpy through the pure_callback bridge
(`mxtpu/ops/custom_op.py`) — the escape hatch for ops XLA can't
express.  The surrounding network still compiles; only the custom node
round-trips to the host.

Run:  python custom_softmax.py [--epochs 5]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import sym


class CustomSoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], mx.nd.array(
            e / e.sum(axis=1, keepdims=True)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # SoftmaxOutput-style gradient: p - onehot(label)
        p = out_data[0].asnumpy().copy()
        label = in_data[1].asnumpy().astype(np.int64)
        p[np.arange(len(label)), label] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(p / len(label)))


@mx.operator.register("custom_softmax")
class CustomSoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0], (in_shape[0][0],)], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return CustomSoftmax()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    mx.random.seed(0)
    np.random.seed(0)
    rng = np.random.RandomState(0)
    templates = rng.uniform(0, 1, (10, 64)).astype(np.float32)
    y = rng.randint(0, 10, 1024)
    X = templates[y] + rng.normal(0, 0.1, (1024, 64)).astype(np.float32)
    it = mx.io.NDArrayIter(X, y.astype(np.float32),
                           batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")

    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=10, name="fc2")
    out = sym.Custom(h, sym.Variable("softmax_label"),
                     op_type="custom_softmax", name="softmax")
    mod = mx.mod.Module(out, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3})
    metric = mx.metric.Accuracy()
    it.reset()
    mod.score(it, metric)
    logging.info("accuracy with host-side custom softmax: %.3f",
                 metric.get()[1])
    assert metric.get()[1] > 0.9


if __name__ == "__main__":
    main()
