"""Matrix factorization for recommendation.

Analog of the reference's `example/sparse/matrix_factorization/` and
`example/recommenders/`: user/item Embedding factors trained on rating
triplets with L2 loss, sparse_grad=True on both tables so each step's
gradient is ROW-SPARSE — only the users/items in the batch get
touched (the `SparseCot` segment-sum path, `mxtpu/autograd.py`).

Run:  python matrix_factorization.py [--factors 16] [--epochs 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon


class MFBlock(gluon.nn.HybridBlock):
    def __init__(self, num_users, num_items, factors):
        super().__init__()
        self.user = gluon.nn.Embedding(num_users, factors,
                                       sparse_grad=True)
        self.item = gluon.nn.Embedding(num_items, factors,
                                       sparse_grad=True)

    def hybrid_forward(self, F, users, items):
        p = self.user(users)
        q = self.item(items)
        return F.sum(p * q, axis=-1)


def synth_ratings(num_users=200, num_items=120, factors=4, n=4096,
                  seed=0):
    """Ratings from a planted low-rank model + noise."""
    rng = np.random.RandomState(seed)
    P = rng.normal(0, 1, (num_users, factors))
    Q = rng.normal(0, 1, (num_items, factors))
    u = rng.randint(0, num_users, n)
    i = rng.randint(0, num_items, n)
    r = (P[u] * Q[i]).sum(1) + rng.normal(0, 0.05, n)
    return (u.astype(np.float32), i.astype(np.float32),
            r.astype(np.float32))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--factors", type=int, default=16)
    p.add_argument("--epochs", type=int, default=8)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    users, items, ratings = synth_ratings()
    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = MFBlock(200, 120, args.factors)
    net.initialize(mx.initializer.Normal(0.05), ctx=ctx)
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    it = mx.io.NDArrayIter({"user": users, "item": items}, ratings,
                           batch_size=args.batch_size, shuffle=True,
                           label_name="score")
    first = last = None
    for epoch in range(args.epochs):
        it.reset()
        total = n = 0.0
        for batch in it:
            u = batch.data[0].as_in_context(ctx)
            i = batch.data[1].as_in_context(ctx)
            r = batch.label[0].as_in_context(ctx)
            with autograd.record():
                loss = loss_fn(net(u, i), r)
            loss.backward()
            trainer.step(u.shape[0])
            total += float(loss.mean().asnumpy())
            n += 1
        if first is None:
            first = total / n
        last = total / n
        logging.info("epoch %d MSE %.4f", epoch, last)
    assert last < first * 0.5, "factorization should fit planted model"


if __name__ == "__main__":
    main()
