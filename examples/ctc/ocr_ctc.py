"""CTC sequence recognition, miniature OCR.

Analog of the reference's `example/ctc/` (warp-ctc OCR): a conv+BiLSTM
reads a rendered digit strip and CTCLoss aligns the unsegmented
character sequence.  Decoding is best-path (greedy) collapse.

Run:  python ocr_ctc.py [--epochs 12]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

VOCAB = 5           # digit classes 0..4; CTC blank is index VOCAB
SEQ = 3             # digits per strip
GLYPH_W = 6
IMG_H = 8


def _glyphs(rng):
    g = np.zeros((VOCAB, IMG_H, GLYPH_W), np.float32)
    for k in range(VOCAB):
        # distinct deterministic stripe patterns per class
        g[k, (k + 1) % IMG_H, :] = 1.0
        g[k, :, k % GLYPH_W] = 1.0
    return g


def make_data(n, seed=0):
    rng = np.random.RandomState(seed)
    glyphs = _glyphs(rng)
    X = np.zeros((n, 1, IMG_H, SEQ * GLYPH_W), np.float32)
    Y = np.zeros((n, SEQ), np.float32)
    for i in range(n):
        digits = rng.randint(0, VOCAB, SEQ)
        for j, d in enumerate(digits):
            X[i, 0, :, j * GLYPH_W:(j + 1) * GLYPH_W] = glyphs[d]
        X[i] += rng.normal(0, 0.05, X[i].shape)
        Y[i] = digits
    return X, Y


class OCRNet(gluon.nn.HybridBlock):
    def __init__(self):
        super().__init__()
        self.conv = gluon.nn.Conv2D(8, 3, padding=1, activation="relu")
        self.lstm = gluon.rnn.LSTM(32, layout="NTC")
        self.proj = gluon.nn.Dense(VOCAB + 1, flatten=False)

    def hybrid_forward(self, F, x):
        f = self.conv(x)                       # (N, 8, H, W)
        f = F.transpose(f, axes=(0, 3, 1, 2))  # (N, W, 8, H): W = time
        f = F.Reshape(f, shape=(0, 0, -1))
        h = self.lstm(f)
        return self.proj(h)                    # (N, W, VOCAB+1)


def greedy_decode(logits):
    """Best-path CTC decoding: argmax per step, collapse repeats,
    drop blanks."""
    path = logits.argmax(axis=-1)
    out = []
    for row in path:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != VOCAB:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=12)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = OCRNet()
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 4e-3})
    X, Y = make_data(512)
    it = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size,
                           shuffle=True, label_name="label")
    for epoch in range(args.epochs):
        it.reset()
        tot = n = 0.0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            y = batch.label[0].as_in_context(ctx)
            with autograd.record():
                logits = net(x)
                # CTCLoss wants (T, N, C) activations
                loss = nd.CTCLoss(nd.transpose(logits, axes=(1, 0, 2)),
                                  y, blank_label="last")
            loss.backward()
            trainer.step(x.shape[0])
            tot += float(loss.mean().asnumpy())
            n += 1
        logging.info("epoch %d CTC loss %.4f", epoch, tot / n)

    logits = net(nd.array(X[:64], ctx=ctx)).asnumpy()
    decoded = greedy_decode(logits)
    exact = sum(1 for d, y in zip(decoded, Y[:64])
                if d == [int(v) for v in y])
    logging.info("exact-sequence accuracy: %d/64", exact)
    # chance exact-match is (1/5)^3 < 1%% — well above that
    # proves the CTC alignment is learning
    assert exact > 10, "CTC should learn the strip alphabet"


if __name__ == "__main__":
    main()
