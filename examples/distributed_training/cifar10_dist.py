"""Distributed data-parallel training (dist_sync kvstore).

Analog of the reference's `example/distributed_training/cifar10_dist.py`:
each worker trains a small convnet on its shard; gradients synchronize
through the parameter-server kvstore (`dist_sync`) or, single-process,
through the mesh-collective store (`--kvstore tpu`).

Launch distributed (2 workers, 1 server):
    python tools/launch.py -n 2 -s 1 python \
        examples/distributed_training/cifar10_dist.py --kvstore dist_sync
Single process:
    python examples/distributed_training/cifar10_dist.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import sym


def build_net(num_classes=10):
    data = sym.Variable("data")
    h = sym.Convolution(data, kernel=(3, 3), num_filter=16, pad=(1, 1),
                        name="conv1")
    h = sym.Activation(h, act_type="relu")
    h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    h = sym.Convolution(h, kernel=(3, 3), num_filter=32, pad=(1, 1),
                        name="conv2")
    h = sym.Activation(h, act_type="relu")
    h = sym.Pooling(h, global_pool=True, pool_type="avg")
    h = sym.FullyConnected(sym.Flatten(h), num_hidden=num_classes,
                           name="fc")
    return sym.SoftmaxOutput(h, sym.Variable("softmax_label"),
                             name="softmax")


def make_data(rank, num_workers, n=2048, seed=7):
    """Deterministic CIFAR-shaped synthetic set, sharded by rank the way
    the reference shards the record file."""
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[:32, :32] / 32.0
    templates = np.stack([
        np.stack([np.sin(2 * np.pi * (k * xx / 10 + c / 3)) for c in
                  range(3)]) for k in range(10)]).astype(np.float32)
    y = rng.randint(0, 10, n)
    X = templates[y] + rng.normal(0, 0.15, (n, 3, 32, 32)) \
        .astype(np.float32)
    X, y = X[rank::num_workers], y[rank::num_workers]
    return X, y.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--kvstore", default="local")
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    np.random.seed(0)

    kv = mx.kv.create(args.kvstore)
    logging.info("kvstore=%s rank=%d/%d", kv.type, kv.rank,
                 kv.num_workers)
    X, y = make_data(kv.rank, kv.num_workers)
    it = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                           shuffle=True, label_name="softmax_label")
    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    mod = mx.mod.Module(build_net(), context=ctx,
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=args.epochs, kvstore=kv, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr,
                              "momentum": 0.9},
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       10))
    metric = mx.metric.Accuracy()
    it.reset()
    mod.score(it, metric)
    logging.info("rank %d final shard accuracy: %.3f", kv.rank,
                 metric.get()[1])


if __name__ == "__main__":
    main()
