#!/usr/bin/env python
"""Gluon image-classification training (hybridized model zoo).

The analog of the reference's `example/gluon/image_classification.py`
(BASELINE.json config #2): a model-zoo network, `hybridize()` compiles
the whole forward+backward to one XLA module, `Trainer` aggregates
through the kvstore.  `--dataset dummy` runs on synthetic data (the
reference's benchmark mode).
"""
import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon.model_zoo import vision


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--dataset", default="dummy", choices=["dummy"])
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--iters-per-epoch", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--no-hybridize", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    image_shape = tuple(int(x) for x in args.image_shape.split(","))

    net = getattr(vision, args.model)(classes=args.classes)
    net.initialize(ctx=ctx)
    if not args.no_hybridize:
        net.hybridize()

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    rng = np.random.RandomState(0)
    for epoch in range(args.epochs):
        metric = mx.metric.Accuracy()
        tic = time.time()
        n_img = 0
        for it in range(args.iters_per_epoch):
            x = mx.nd.array(rng.rand(args.batch_size, *image_shape)
                            .astype(np.float32), ctx=ctx)
            y = mx.nd.array(rng.randint(0, args.classes, args.batch_size)
                            .astype(np.float32), ctx=ctx)
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([y], [out])
            n_img += args.batch_size
        mx.nd.waitall()
        name, acc = metric.get()
        logging.info("epoch %d: %s=%.4f, %.1f img/s", epoch, name, acc,
                     n_img / (time.time() - tic))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
