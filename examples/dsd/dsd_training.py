"""Dense-Sparse-Dense training — the reference's `example/dsd/` role
(Han et al. 2017): train dense, prune the smallest-magnitude weights
and retrain under the sparsity mask (the S phase), then remove the
mask and retrain dense again (the final D) — the regularize-then-
re-expand recipe.  The mask is applied by zeroing gradients AND
weights after each update, the way the paper's sparse phase operates.

Run:  python dsd_training.py [--phase-epochs 6]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


def make_data(rng, W, n=600, dim=30):
    # train and val must share the SAME ground-truth W
    X = rng.randn(n, dim).astype(np.float32)
    y = (X @ W + 0.5 * rng.randn(n, W.shape[1])).argmax(1) \
        .astype(np.float32)
    return X, y


def accuracy(net, X, y):
    return float((net(nd.array(X)).asnumpy().argmax(1) == y).mean())


def train_phase(net, trainer, X, y, epochs, masks=None, bs=50):
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    it = mx.io.NDArrayIter(X, y, batch_size=bs, shuffle=True)
    for _ in range(epochs):
        it.reset()
        for batch in it:
            with autograd.record():
                loss = loss_fn(net(batch.data[0]),
                               batch.label[0]).mean()
            loss.backward()
            trainer.step(1)
            if masks is not None:   # sparse phase: re-zero pruned slots
                for name, p in net.collect_params().items():
                    if name in masks:
                        p.set_data(p.data() * masks[name])
    return float(loss.asnumpy())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--phase-epochs", type=int, default=6)
    ap.add_argument("--sparsity", type=float, default=0.5)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--seed", type=int, default=31)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    W_true = rng.randn(30, 5) * 2
    X, y = make_data(rng, W_true)
    Xv, yv = make_data(rng, W_true, n=200)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(5))
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    # --- D: dense ---
    train_phase(net, trainer, X, y, args.phase_epochs)
    acc_d = accuracy(net, Xv, yv)
    logging.info("phase D  (dense)  accuracy %.3f", acc_d)

    # --- S: prune smallest |w| per layer, retrain masked ---
    masks = {}
    for name, p in net.collect_params().items():
        if "weight" not in name:
            continue
        w = p.data().asnumpy()
        k = int(w.size * args.sparsity)
        thresh = np.sort(np.abs(w).ravel())[k]
        m = (np.abs(w) >= thresh).astype(np.float32)
        masks[name] = nd.array(m)
        p.set_data(p.data() * masks[name])
    train_phase(net, trainer, X, y, args.phase_epochs, masks=masks)
    acc_s = accuracy(net, Xv, yv)
    nz = float(np.mean([float(m.asnumpy().mean())
                        for m in masks.values()]))
    logging.info("phase S  (sparse %.0f%% kept) accuracy %.3f",
                 nz * 100, acc_s)

    # --- D: re-densify (mask off), lower lr ---
    trainer.set_learning_rate(args.lr * 0.3)
    train_phase(net, trainer, X, y, args.phase_epochs)
    acc_final = accuracy(net, Xv, yv)
    logging.info("phase D2 (re-dense) accuracy %.3f", acc_final)
    print("FINAL_ACCURACY %.4f" % acc_final)


if __name__ == "__main__":
    main()
