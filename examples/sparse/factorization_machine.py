"""Factorization machine on sparse input — the reference's
`example/sparse/factorization_machine/` role: second-order FM
(Rendle 2010) over high-dimensional sparse features, CSR batches, and
the O(nnz·k) interaction identity  0.5·((x·V)² − x²·V²)  instead of
the naive O(d²) pair sum.

Synthetic task: click prediction where the label depends ONLY on
feature co-occurrence pairs — a linear model cannot beat the
majority-class baseline, the FM must.

Run:  python factorization_machine.py [--epochs 30]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, nd

D = 2000          # feature dimension (sparse)
K = 8             # factor rank
PAIRS = [(17, 412), (901, 1203), (55, 1999), (333, 777), (64, 128)]


def make_data(rng, n):
    X = (rng.rand(n, D) < 0.01).astype(np.float32)
    for i, j in PAIRS:       # boost pair co-occurrence frequency
        on = rng.rand(n) < 0.25
        X[on, i] = 1
        X[on, j] = 1
    score = sum(X[:, i] * X[:, j] for i, j in PAIRS)
    y = (score > 0).astype(np.float32)
    return X, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=200)
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    Xtr, ytr = make_data(rng, 4000)
    Xte, yte = make_data(rng, 1000)
    base = max(yte.mean(), 1 - yte.mean())

    w = nd.zeros((D, 1))
    V = nd.random.normal(0, 0.05, (D, K))
    b = nd.zeros((1,))
    for p in (w, V, b):
        p.attach_grad()

    def fm(xb):
        # works for CSR inputs (sparse gather-dot) and dense alike;
        # features are BINARY, so x**2 == x and the second interaction
        # term reuses the same sparse product
        from mxtpu.ndarray import sparse as sp

        dot = sp.dot if isinstance(xb, sp.CSRNDArray) else nd.dot
        lin = dot(xb, w).reshape((-1,)) + b
        xv = dot(xb, V)
        inter = 0.5 * ((xv ** 2).sum(axis=1) - dot(xb, V ** 2)
                       .sum(axis=1))
        return lin + inter

    def logloss(z, t):
        return (nd.relu(z) - z * t +
                nd.log(1 + nd.exp(-nd.abs(z)))).mean()

    n = len(Xtr)
    for epoch in range(args.epochs):
        lsum, nb = 0.0, 0
        for i in range(0, n, args.batch_size):
            # CSR batch through the taped sparse dot path
            xb = mx.nd.sparse.csr_matrix(Xtr[i:i + args.batch_size])
            yb = nd.array(ytr[i:i + args.batch_size])
            with autograd.record():
                loss = logloss(fm(xb), yb)
            loss.backward()
            for p in (w, V, b):
                p -= args.lr * p.grad
                p.grad[:] = 0
            lsum += float(loss.asnumpy())
            nb += 1
        if (epoch + 1) % 10 == 0 or epoch == args.epochs - 1:
            pred = (fm(nd.array(Xte)).asnumpy() > 0)
            acc = float((pred == yte).mean())
            logging.info("epoch %d logloss %.4f test acc %.3f "
                         "(majority %.3f)", epoch, lsum / nb, acc,
                         base)
    print("FINAL_ACCURACY %.4f" % acc)


if __name__ == "__main__":
    main()
