"""Wide & Deep on sparse features — the reference's
`example/sparse/wide_deep/` role (Cheng et al. 2016, census-income
style): a WIDE sparse-linear arm over one-hot/cross features joined
with a DEEP arm of embeddings + MLP over the categorical ids, trained
jointly on logistic loss.

Synthetic census-like task: 4 categorical fields; the label mixes a
direct single-feature signal (wide's specialty) with a nonlinear
cross-field interaction (deep's specialty) — each arm alone plateaus,
jointly they pass the threshold.

Run:  python wide_deep.py [--epochs 12]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

FIELDS = [40, 30, 20, 10]          # cardinality per categorical field
WIDE_D = sum(FIELDS)


def make_data(rng, n):
    cats = np.stack([rng.randint(0, c, n) for c in FIELDS], 1)
    # wide signal: a memorizable single-feature rule; deep signal: a
    # cross-field parity interaction no linear model can represent —
    # label = OR of the two, with 8% flip noise
    wide_rule = cats[:, 0] < 8
    deep_rule = (cats[:, 1] % 2) == (cats[:, 2] % 2)
    y = (wide_rule | deep_rule).astype(np.float32)
    flip = rng.rand(n) < 0.08
    y[flip] = 1 - y[flip]
    # one-hot wide features
    wide = np.zeros((n, WIDE_D), np.float32)
    off = 0
    for f, c in enumerate(FIELDS):
        wide[np.arange(n), off + cats[:, f]] = 1
        off += c
    return cats.astype(np.float32), wide, y


class WideDeep(gluon.nn.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            # wide arm: sparse linear over the one-hot vector
            self.wide = gluon.nn.Dense(1, use_bias=True)
            # deep arm: per-field embeddings -> MLP
            self.embs = [gluon.nn.Embedding(c, 8, prefix="emb%d_" % i)
                         for i, c in enumerate(FIELDS)]
            for e in self.embs:
                self.register_child(e)
            self.mlp = gluon.nn.HybridSequential()
            self.mlp.add(gluon.nn.Dense(32, activation="relu"),
                         gluon.nn.Dense(16, activation="relu"),
                         gluon.nn.Dense(1))

    def hybrid_forward(self, F, cats, wide):
        embs = [e(cats[:, i]) for i, e in enumerate(self.embs)]
        deep = self.mlp(F.concat(*embs, dim=1))
        return (self.wide(wide) + deep).reshape((-1,))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--seed", type=int, default=9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    cats, wide, y = make_data(rng, 6000)
    catv, widev, yv = make_data(rng, 1500)
    base = max(yv.mean(), 1 - yv.mean())

    net = WideDeep()
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    it = mx.io.NDArrayIter({"cats": cats, "wide": wide}, y,
                           batch_size=args.batch_size, shuffle=True)
    for epoch in range(args.epochs):
        it.reset()
        lsum, nb = 0.0, 0
        for b in it:
            with autograd.record():
                logit = net(b.data[0], b.data[1])
                loss = loss_fn(logit, b.label[0]).mean()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
            nb += 1
        pred = (net(nd.array(catv), nd.array(widev)).asnumpy() > 0)
        acc = float((pred == yv).mean())
        logging.info("epoch %d loss %.4f val acc %.3f (majority %.3f)",
                     epoch, lsum / nb, acc, base)
    print("FINAL_ACCURACY %.4f" % acc)


if __name__ == "__main__":
    main()
