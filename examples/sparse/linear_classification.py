"""Sparse linear classification (BASELINE config #5).

TPU-native counterpart of the reference's
`example/sparse/linear_classification/train.py`: a two-class linear
model over million-feature libsvm data where

  * batches are CSRNDArrays (`mxtpu.io.LibSVMIter` parses straight to
    CSR triplets — nothing densifies),
  * the weight GRADIENT is row-sparse: `sparse.dot(csr, W)` tapes a
    vjp whose cotangent holds only the features present in the batch
    (`mxtpu/ndarray/sparse.py` dot; reference DotCsrTransDnsRspImpl),
  * the optimizer applies LAZY row updates (SGD/AdaGrad touch only the
    gradient's rows — reference `_sparse_adagrad_update`,
    `sgd_update` with row_sparse grad),
  * with --kvstore dist_*, gradients travel as rows-only pushes and
    weights return via `row_sparse_pull` (reference PullRowSparse,
    `src/kvstore/kvstore_dist.h`) — wire traffic is O(batch nnz), not
    O(num_features).

The reference downloads the Avazu CTR dataset; this environment has no
egress, so --synthesize generates an Avazu-shaped file (same libsvm
format, power-law feature popularity) with a planted linear concept so
accuracy is checkable.

Run:  python linear_classification.py --synthesize
Dist: python tools/launch.py -n 2 -s 1 python \
          examples/sparse/linear_classification.py --synthesize \
          --kvstore dist_sync
"""
import argparse
import logging
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import mxtpu as mx
from mxtpu import autograd, nd, optimizer as opt_mod
from mxtpu.io.io import LibSVMIter
from mxtpu.ndarray import sparse as sp


def synthesize(path, num_rows=4000, num_features=100000, nnz_per_row=20,
               seed=0):
    """Avazu-shaped libsvm file: power-law feature ids, binary labels
    from a planted sparse linear concept (so training is verifiable)."""
    rng = np.random.RandomState(seed)
    true_w = np.zeros(num_features, np.float32)
    hot = rng.choice(num_features, size=2000, replace=False)
    true_w[hot] = rng.randn(2000)
    with open(path, "w") as f:
        for _ in range(num_rows):
            # power-law popularity: low ids much more frequent
            feats = np.unique(
                (num_features * rng.power(0.25, size=nnz_per_row))
                .astype(np.int64) % num_features)
            vals = np.ones(len(feats), np.float32)
            margin = float(true_w[feats].sum())
            label = 1 if margin + 0.1 * rng.randn() > 0 else 0
            cols = " ".join("%d:%g" % (k, v)
                            for k, v in zip(feats, vals))
            f.write("%d %s\n" % (label, cols))
    return path


def forward(batch, weight, bias):
    """logits = csr · W + b   (sparse dot tapes a row-sparse W-grad)."""
    logits = sp.dot(batch.data[0], weight)
    return mx.nd.broadcast_add(logits, bias)


def loss_fn(logits, label, positive_cls_weight):
    """Weighted softmax cross-entropy (reference
    `weighted_softmax_ce.py`): positive instances upweighted to combat
    class imbalance."""
    logp = mx.nd.log_softmax(logits)
    lab = label.asnumpy().astype(np.int64)
    onehot = mx.nd.one_hot(label, depth=2)
    w = nd.array(np.where(lab == 1, positive_cls_weight, 1.0)
                 .astype(np.float32))
    per = -(logp * onehot).sum(axis=1) * w
    return per.sum() / max(1, len(lab))


def evaluate(it, weight, bias):
    it.reset()
    correct = total = 0
    for batch in it:
        logits = forward(batch, weight, bias)
        pred = np.argmax(logits.asnumpy(), axis=1)
        lab = batch.label[0].asnumpy()
        n = len(lab) - (batch.pad or 0)
        correct += (pred[:n] == lab[:n]).sum()
        total += n
    return correct / max(1, total)


def main():
    p = argparse.ArgumentParser(
        description="sparse linear classification (reference "
                    "example/sparse/linear_classification)")
    p.add_argument("--num-epoch", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--kvstore", type=str, default=None,
                   choices=[None, "local", "dist_sync", "dist_async"])
    p.add_argument("--optimizer", type=str, default="adagrad",
                   choices=["sgd", "adagrad"])
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--num-features", type=int, default=100000)
    p.add_argument("--num-rows", type=int, default=4000)
    p.add_argument("--synthesize", action="store_true",
                   help="generate the Avazu-shaped dataset (no egress)")
    p.add_argument("--data", type=str, default=None)
    p.add_argument("--min-accuracy", type=float, default=0.0,
                   help="exit nonzero if final train accuracy is below")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)-15s %(message)s")

    kv = mx.kv.create(args.kvstore) if args.kvstore else None
    rank = kv.rank if kv else 0
    num_workers = kv.num_workers if kv else 1

    data_path = args.data
    if args.synthesize or data_path is None:
        data_path = os.path.join(
            os.environ.get("MXTPU_DATA_DIR", "/tmp"),
            "avazu_synth_%d.libsvm" % args.num_features)
        if rank == 0 and not os.path.exists(data_path):
            synthesize(data_path, num_rows=args.num_rows,
                       num_features=args.num_features)
        if kv:
            kv.barrier()  # wait for rank 0 to write the file

    train_it = LibSVMIter(data_libsvm=data_path,
                          data_shape=(args.num_features,),
                          batch_size=args.batch_size,
                          num_parts=num_workers, part_index=rank)

    rng = np.random.RandomState(1)
    weight = nd.array(rng.normal(0, 0.01, (args.num_features, 2))
                      .astype(np.float32))
    bias = nd.array(np.zeros((2,), np.float32))
    weight.attach_grad(stype="row_sparse")
    bias.attach_grad()

    optimizer = opt_mod.create(
        args.optimizer, learning_rate=args.lr,
        rescale_grad=1.0 / args.batch_size / num_workers)
    updater = opt_mod.get_updater(optimizer)

    if kv:
        kv.init("weight", weight)
        kv.init("bias", bias)
        kv.set_optimizer(optimizer)

    logging.info("training started (rank %d/%d, %s)", rank, num_workers,
                 args.kvstore or "local updater")
    acc = 0.0
    for epoch in range(args.num_epoch):
        train_it.reset()
        t0 = time.time()
        nbatch = 0
        for batch in train_it:
            if kv:
                # ship ONLY this batch's feature rows over the wire
                kv.row_sparse_pull("weight", out=weight,
                                   row_ids=batch.data[0].indices)
                kv.pull("bias", out=bias)
            with autograd.record():
                logits = forward(batch, weight, bias)
                loss = loss_fn(logits, batch.label[0], 2.0)
            loss.backward()
            if kv:
                kv.push("weight", weight.grad)   # rows-only push
                kv.push("bias", bias.grad)
            else:
                updater(0, weight.grad, weight)  # lazy row update
                updater(1, bias.grad, bias)
            nbatch += 1
        if kv:  # fetch the full weight for evaluation
            kv.row_sparse_pull(
                "weight", out=weight,
                row_ids=nd.array(np.arange(args.num_features,
                                           dtype=np.float32)))
            kv.pull("bias", out=bias)
        acc = evaluate(train_it, weight, bias)
        logging.info("epoch %d: train-accuracy=%.4f (%.1fs, %d batches)",
                     epoch, acc, time.time() - t0, nbatch)
    print("FINAL_ACCURACY %.4f" % acc)
    if kv:
        # leave the PS cleanly before exiting
        if hasattr(kv, "close"):
            kv.close()
    if acc < args.min_accuracy:
        sys.exit(1)


if __name__ == "__main__":
    main()
