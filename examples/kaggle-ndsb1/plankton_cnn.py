"""Kaggle NDSB plankton classification — the reference's
`example/kaggle-ndsb1/` + `kaggle-ndsb2/` role: a many-class
small-image competition pipeline — train/val split, an aspect-
preserving resize + augmentation stage (random flips/rotations via the
image augmenter pipeline), a compact CNN, and multiclass log-loss (the
competition metric) alongside accuracy.

Synthetic data: 8 "plankton genera" rendered as distinct silhouettes
(rings, rods, stars...) with random orientation/scale — mimicking the
shape-dominant, rotation-invariant nature of the real dataset.

Run:  python plankton_cnn.py [--epochs 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

IMG = 24
N_CLASS = 8


def render(rng, cls):
    x = np.zeros((IMG, IMG), np.float32)
    c = IMG // 2
    yy, xx = np.mgrid[0:IMG, 0:IMG]
    r = np.sqrt((yy - c) ** 2 + (xx - c) ** 2)
    ang = np.arctan2(yy - c, xx - c)
    s = rng.uniform(0.7, 1.1)
    if cls == 0:    x[(r > 6 * s) & (r < 9 * s)] = 1            # ring
    elif cls == 1:  x[np.abs(yy - c) < 2] = 1                   # rod
    elif cls == 2:  x[r < 7 * s] = 1                            # disc
    elif cls == 3:  x[(r < 9 * s) & (np.cos(3 * ang) > 0.3)] = 1  # tri-star
    elif cls == 4:  x[(r < 9 * s) & (np.cos(5 * ang) > 0.3)] = 1  # 5-star
    elif cls == 5:  x[(np.abs(yy - c) < 2) | (np.abs(xx - c) < 2)] = 1
    elif cls == 6:  x[(r > 3 * s) & (r < 5 * s)] = 1            # small ring
    else:           x[(np.abs(yy - xx) < 3)] = 1                # diagonal
    # competition-style augmentation: random rotation by 90s + flips
    k = rng.randint(0, 4)
    x = np.rot90(x, k)
    if rng.rand() < 0.5:
        x = np.fliplr(x)
    return x + 0.1 * rng.randn(IMG, IMG).astype(np.float32)


def make_data(rng, n):
    ys = rng.randint(0, N_CLASS, n)
    xs = np.stack([render(rng, c) for c in ys])[:, None]
    return xs.astype(np.float32), ys.astype(np.float32)


def build_net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dropout(0.2),
            gluon.nn.Dense(N_CLASS))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=41)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    net = build_net()
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    Xv, yv = make_data(rng, 160)
    for epoch in range(args.epochs):
        lsum = 0.0
        for _ in range(15):
            x, y = make_data(rng, args.batch_size)  # fresh augmented
            with autograd.record():
                loss = loss_fn(net(nd.array(x)), nd.array(y)).mean()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
        logits = net(nd.array(Xv)).asnumpy()
        p = np.exp(logits - logits.max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        logloss = float(-np.log(p[np.arange(len(yv)),
                                  yv.astype(int)] + 1e-12).mean())
        acc = float((logits.argmax(1) == yv).mean())
        logging.info("epoch %d loss %.4f val logloss %.4f acc %.3f",
                     epoch, lsum / 15, logloss, acc)
    print("FINAL_LOGLOSS %.4f" % logloss)


if __name__ == "__main__":
    main()
