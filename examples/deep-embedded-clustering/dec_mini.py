"""Deep embedded clustering — the reference's
`example/deep-embedded-clustering/` pipeline (Xie et al. 2016): 1)
autoencoder pretraining, 2) k-means init of cluster centroids in
latent space, 3) joint refinement minimizing KL(P || Q) between the
Student-t soft assignment Q and the sharpened target P, with
best-map cluster accuracy reported.

Synthetic data: 4 Gaussian blobs embedded nonlinearly into 16-D.

Run:  python dec_mini.py [--pretrain-epochs 20] [--dec-iters 80]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import itertools
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

K = 4
DIM = 16
LATENT = 2


def make_data(rng, n_per=120):
    centers = np.array([[3, 0], [-3, 0], [0, 3], [0, -3]], np.float32)
    z = np.concatenate([c + 0.5 * rng.randn(n_per, 2) for c in centers])
    y = np.repeat(np.arange(K), n_per)
    A = rng.randn(2, DIM).astype(np.float32)
    X = np.tanh(z @ A) + 0.05 * rng.randn(len(z), DIM)
    perm = rng.permutation(len(z))
    return X[perm].astype(np.float32), y[perm]


def kmeans(z, k, rng, iters=30):
    c = z[rng.choice(len(z), k, replace=False)]
    for _ in range(iters):
        d = ((z[:, None] - c[None]) ** 2).sum(-1)
        a = d.argmin(1)
        for j in range(k):
            if (a == j).any():
                c[j] = z[a == j].mean(0)
    return c


def best_map_accuracy(pred, y):
    best = 0.0
    for perm in itertools.permutations(range(K)):
        m = np.array(perm)[pred]
        best = max(best, float((m == y).mean()))
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pretrain-epochs", type=int, default=20)
    ap.add_argument("--dec-iters", type=int, default=80)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=21)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    X, y = make_data(rng)
    Xn = nd.array(X)

    enc = gluon.nn.HybridSequential()
    enc.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(LATENT))
    dec = gluon.nn.HybridSequential()
    dec.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(DIM))
    enc.initialize(ctx=mx.cpu())
    dec.initialize(ctx=mx.cpu())
    params = gluon.ParameterDict()
    params.update(enc.collect_params())
    params.update(dec.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})

    # 1) autoencoder pretraining
    for epoch in range(args.pretrain_epochs):
        with autograd.record():
            recon = dec(enc(Xn))
            loss = ((recon - Xn) ** 2).mean()
        loss.backward()
        trainer.step(1)
    logging.info("pretrain reconstruction loss %.4f",
                 float(loss.asnumpy()))

    # 2) k-means init in latent space
    z = enc(Xn).asnumpy()
    centroids = nd.array(kmeans(z, K, rng))
    centroids.attach_grad()
    dec_trainer = gluon.Trainer(enc.collect_params(), "adam",
                                {"learning_rate": args.lr})

    # 3) DEC refinement: Student-t Q, sharpened target P
    for it in range(args.dec_iters):
        with autograd.record():
            z = enc(Xn)
            d2 = ((z.expand_dims(1) - centroids.expand_dims(0)) ** 2) \
                .sum(axis=-1)
            q = 1.0 / (1.0 + d2)
            q = q / q.sum(axis=1, keepdims=True)
            qn = q.detach().asnumpy()
            p = qn ** 2 / qn.sum(0, keepdims=True)
            p = nd.array(p / p.sum(1, keepdims=True))
            kl = (p * (nd.log(p + 1e-9) - nd.log(q + 1e-9))) \
                .sum(axis=1).mean()
        kl.backward()
        dec_trainer.step(1)
        centroids -= args.lr * centroids.grad
        if (it + 1) % 20 == 0:
            acc = best_map_accuracy(qn.argmax(1), y)
            logging.info("dec iter %d KL %.4f cluster accuracy %.3f",
                         it + 1, float(kl.asnumpy()), acc)
    acc = best_map_accuracy(qn.argmax(1), y)
    print("FINAL_CLUSTER_ACCURACY %.4f" % acc)


if __name__ == "__main__":
    main()
