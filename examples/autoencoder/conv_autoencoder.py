"""Convolutional autoencoder.

Analog of the reference's `example/autoencoder/`: encoder convs down to
a small code, decoder `Conv2DTranspose`s back; trained with L2 loss.
Exercises Deconvolution through gluon + hybridize (the decoder is the
input-dilated transposed-conv path of `mxtpu/ops/nn.py`).

Run:  python conv_autoencoder.py [--epochs 5] [--code-dim 16]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon


class ConvAE(gluon.nn.HybridBlock):
    def __init__(self, code_dim=16):
        super().__init__()
        self.encoder = gluon.nn.HybridSequential()
        self.encoder.add(
            gluon.nn.Conv2D(8, 3, strides=2, padding=1,
                            activation="relu"),     # 28 -> 14
            gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                            activation="relu"),     # 14 -> 7
            gluon.nn.Flatten(),
            gluon.nn.Dense(code_dim))
        self.decoder_fc = gluon.nn.Dense(16 * 7 * 7, activation="relu")
        self.decoder = gluon.nn.HybridSequential()
        self.decoder.add(
            gluon.nn.Conv2DTranspose(8, 4, strides=2, padding=1,
                                     activation="relu"),  # 7 -> 14
            gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                                     activation="sigmoid"))  # 14 -> 28

    def hybrid_forward(self, F, x):
        code = self.encoder(x)
        h = self.decoder_fc(code)
        h = F.Reshape(h, shape=(-1, 16, 7, 7))
        return self.decoder(h)


def synthetic_digits(n=512, seed=0):
    rng = np.random.RandomState(seed)
    base = np.zeros((n, 1, 28, 28), np.float32)
    for i in range(n):
        cx, cy = rng.randint(6, 22, 2)
        r = rng.randint(3, 7)
        yy, xx = np.mgrid[:28, :28]
        base[i, 0] = ((yy - cy) ** 2 + (xx - cx) ** 2 < r * r)
    return base + rng.normal(0, 0.02, base.shape).astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--code-dim", type=int, default=16)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = ConvAE(args.code_dim)
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    net.hybridize()
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    X = synthetic_digits()
    it = mx.io.NDArrayIter(X, batch_size=args.batch_size, shuffle=True)
    first = last = None
    for epoch in range(args.epochs):
        it.reset()
        total = n = 0.0
        for batch in it:
            x = batch.data[0].as_in_context(ctx)
            with autograd.record():
                loss = loss_fn(net(x), x)
            loss.backward()
            trainer.step(x.shape[0])
            total += float(loss.mean().asnumpy())
            n += 1
        if first is None:
            first = total / n
        last = total / n
        logging.info("epoch %d reconstruction loss %.5f", epoch, last)
    assert last < first, "reconstruction loss should decrease"


if __name__ == "__main__":
    main()
