"""Multiclass SVM head (SVMOutput).

Analog of the reference's `example/svm_mnist/svm_mnist.py`: same MLP,
but the head is `SVMOutput` — hinge loss (L1 or squared L2) with
margin, instead of softmax cross-entropy.

Run:  python svm_mnist.py [--l2] [--epochs 5]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import sym


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--l2", action="store_true",
                   help="squared hinge instead of L1 hinge")
    p.add_argument("--margin", type=float, default=1.0)
    p.add_argument("--reg-coeff", type=float, default=1.0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    rng = np.random.RandomState(0)
    templates = rng.uniform(0, 1, (10, 128)).astype(np.float32)
    y = rng.randint(0, 10, 2048)
    X = templates[y] + rng.normal(0, 0.15, (2048, 128)) \
        .astype(np.float32)
    it = mx.io.NDArrayIter(X, y.astype(np.float32),
                           batch_size=args.batch_size, shuffle=True,
                           label_name="svm_label")

    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=128, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=10, name="fc2")
    out = sym.SVMOutput(h, sym.Variable("svm_label"),
                        margin=args.margin,
                        regularization_coefficient=args.reg_coeff,
                        use_linear=not args.l2, name="svm")
    mod = mx.mod.Module(out, context=mx.cpu(), label_names=("svm_label",))
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    metric = mx.metric.Accuracy()
    it.reset()
    mod.score(it, metric)
    logging.info("SVM (%s hinge) accuracy: %.3f",
                 "L1" if not args.l2 else "squared-L2", metric.get()[1])
    assert metric.get()[1] > 0.9


if __name__ == "__main__":
    main()
