"""Profiler walkthrough.

Analog of the reference's `example/profiler/profiler_executor.py`:
profile a training step and dump a chrome://tracing file plus the
aggregate table (`mxtpu.profiler`).

Run:  python profiler_demo.py [--out profile.json]
Open the JSON in chrome://tracing or https://ui.perfetto.dev.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import json
import logging

import numpy as np

import mxtpu as mx
from mxtpu import sym


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="profile.json")
    p.add_argument("--steps", type=int, default=5)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=256, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=10, name="fc2")
    net = sym.SoftmaxOutput(h, sym.Variable("softmax_label"),
                            name="softmax")
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, (1024, 128)).astype(np.float32)
    Y = rng.randint(0, 10, 1024).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=128,
                           label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer()

    mx.profiler.set_config(filename=args.out, profile_symbolic=True,
                           profile_imperative=True, profile_memory=True)
    mx.profiler.set_state("run")
    for i, batch in enumerate(it):
        if i >= args.steps:
            break
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    mx.nd.waitall()
    mx.profiler.set_state("stop")
    print(mx.profiler.dumps())          # aggregate table
    mx.profiler.dump()                  # chrome trace file
    assert os.path.exists(args.out)
    with open(args.out) as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    logging.info("wrote %s with %d trace events", args.out, len(events))


if __name__ == "__main__":
    main()
