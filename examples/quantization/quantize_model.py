"""Post-training INT8 quantization walkthrough.

Analog of the reference's `example/quantization/imagenet_gen_qsym.py`:
train (briefly), calibrate on held-out batches, rewrite the graph to
int8 islands (`mxtpu.contrib.quantization`, riding the subgraph
framework), and compare fp32 vs int8 top-1 agreement.

Run:  python quantize_model.py [--calib-mode naive|entropy|none]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import sym
from mxtpu.contrib.quantization import quantize_model


def build_net():
    data = sym.Variable("data")
    h = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv1")
    h = sym.Activation(h, act_type="relu")
    h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    h = sym.FullyConnected(sym.Flatten(h), num_hidden=32, name="fc1")
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(h, sym.Variable("softmax_label"),
                             name="softmax")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--calib-mode", default="naive",
                   choices=["none", "naive", "entropy"])
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    np.random.seed(0)

    rng = np.random.RandomState(0)
    yy, xx = np.mgrid[:16, :16] / 16.0
    templates = np.stack([
        np.stack([np.sin(2 * np.pi * (k * xx / 8 + c / 3))
                  for c in range(3)]) for k in range(10)]) \
        .astype(np.float32)
    y = rng.randint(0, 10, 1536)
    X = templates[y] + rng.normal(0, 0.1, (1536, 3, 16, 16)) \
        .astype(np.float32)
    it = mx.io.NDArrayIter(X[:1024], y[:1024].astype(np.float32),
                           batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")
    calib_it = mx.io.NDArrayIter(X[1024:], y[1024:].astype(np.float32),
                                 batch_size=args.batch_size,
                                 label_name="softmax_label")

    mod = mx.mod.Module(build_net(), context=mx.cpu(),
                        label_names=("softmax_label",))
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3})
    arg_params, aux_params = mod.get_params()
    net = mod.symbol

    qsym, qarg, qaux = quantize_model(
        net, arg_params, aux_params, calib_data=calib_it,
        calib_mode=args.calib_mode, num_calib_examples=256)
    q_ops = [n.op.name for n in qsym._topo() if not n.is_variable]
    logging.info("quantized nodes: %d int8 islands",
                 q_ops.count("_contrib_quantize_v2"))

    def predict(s, params, aux):
        arg_names = set(s.list_arguments())
        # quantized params (int8 tables, min/max scalars) have shapes
        # and dtypes infer_shape cannot derive — pass them explicitly
        shapes = {k: tuple(v.shape) for k, v in params.items()
                  if k in arg_names}
        shapes["data"] = (args.batch_size, 3, 16, 16)
        shapes["softmax_label"] = (args.batch_size,)
        tdict = {k: v.dtype for k, v in params.items() if k in arg_names}
        exe = s.simple_bind(ctx=mx.cpu(), grad_req="null",
                            type_dict=tdict, **shapes)
        exe.copy_params_from(params, aux, allow_extra_params=True)
        preds = []
        calib_it.reset()
        for batch in calib_it:
            out = exe.forward(is_train=False, data=batch.data[0])[0]
            preds.append(out.asnumpy().argmax(axis=1))
        return np.concatenate(preds)

    p32 = predict(net, arg_params, aux_params)
    p8 = predict(qsym, qarg, qaux)
    agree = (p32 == p8).mean()
    logging.info("fp32 vs int8 top-1 agreement: %.3f", agree)
    assert agree > 0.9, "int8 predictions should track fp32"


if __name__ == "__main__":
    main()
