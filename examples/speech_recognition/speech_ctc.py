"""Speech recognition, miniature — the role of the reference's
`example/speech_recognition/` (DeepSpeech2-style acoustic model): a
conv front-end over spectrogram-like features, bidirectional LSTM
layers, and CTC alignment-free training (`CTCLoss`), with greedy CTC
decoding + label-error-rate evaluation.

Synthetic task: each "utterance" is a sequence of frequency-band
energy patterns, one pattern per spoken digit, with variable per-digit
duration and noise — the CTC must learn alignment AND classification.

Run:  python speech_ctc.py [--epochs 10]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

N_BANDS = 20      # spectrogram bands
N_DIGITS = 5      # vocabulary (labels 1..5; 0 is CTC blank)
MAX_T = 60        # frames per utterance
MAX_L = 6         # max digits per utterance


def make_utterance(rng):
    """Digits -> band-energy frames: digit d lights up bands
    [3d, 3d+3) for 6-10 frames."""
    n = rng.randint(3, MAX_L + 1)
    digits = rng.randint(1, N_DIGITS + 1, n)
    frames = []
    for d in digits:
        dur = rng.randint(6, 11)
        f = rng.uniform(0, 0.3, (dur, N_BANDS))
        f[:, 3 * (d - 1):3 * (d - 1) + 3] += 1.0
        frames.append(f)
    x = np.concatenate(frames)[:MAX_T]
    pad = np.zeros((MAX_T, N_BANDS), np.float32)
    pad[:len(x)] = x
    lab = np.zeros(MAX_L, np.float32)
    lab[:n] = digits
    return pad.astype(np.float32), lab, len(x), n


def make_batch(rng, bs):
    xs, ys, xl, yl = zip(*[make_utterance(rng) for _ in range(bs)])
    return (np.stack(xs), np.stack(ys), np.array(xl, np.float32),
            np.array(yl, np.float32))


class AcousticModel(gluon.nn.HybridBlock):
    """BiLSTM straight over the band energies (a conv front-end slowed
    CTC's escape from the all-blank phase on this task)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.rnn = gluon.rnn.LSTM(64, num_layers=1,
                                      bidirectional=True)
            self.out = gluon.nn.Dense(N_DIGITS + 1, flatten=False)

    def hybrid_forward(self, F, x):
        # x: (B, T, bands) -> (T, B, bands) for the RNN
        h = self.rnn(x.transpose((1, 0, 2)))
        return self.out(h)  # (T, B, N_DIGITS+1), blank = 0


def greedy_decode(logits):
    """CTC greedy: argmax per frame, collapse repeats, drop blanks."""
    ids = logits.argmax(-1)
    out = []
    for b in range(ids.shape[1]):
        seq, prev = [], -1
        for t in ids[:, b]:
            if t != prev and t != 0:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def edit_distance(a, b):
    dp = np.arange(len(b) + 1, dtype=np.int64)
    for i in range(1, len(a) + 1):
        prev, dp[0] = dp[0], i
        for j in range(1, len(b) + 1):
            cur = min(dp[j] + 1, dp[j - 1] + 1,
                      prev + (a[i - 1] != b[j - 1]))
            prev, dp[j] = dp[j], cur
    return dp[len(b)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--lr", type=float, default=5e-3)
    ap.add_argument("--seed", type=int, default=5)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    model = AcousticModel()
    model.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr})

    for epoch in range(args.epochs):
        lsum = 0.0
        for _ in range(30):
            x, y, xlen, ylen = make_batch(rng, args.batch_size)
            xb = nd.array(x)
            with autograd.record():
                logits = model(xb)
                loss = nd.CTCLoss(logits, nd.array(y),
                                  nd.array(ylen),
                                  use_label_lengths=True).mean()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
        # label error rate on a fresh eval batch
        x, y, xlen, ylen = make_batch(rng, 32)
        decoded = greedy_decode(model(nd.array(x)).asnumpy())
        errs = sum(edit_distance(d, list(y[b][:int(ylen[b])].astype(int)))
                   for b, d in enumerate(decoded))
        total = int(ylen.sum())
        ler = errs / total
        logging.info("epoch %d ctc loss %.4f LER %.3f", epoch,
                     lsum / 30, ler)
    print("FINAL_LER %.4f" % ler)


if __name__ == "__main__":
    main()
