"""Train a sharded TransformerLM on a character copy-task corpus.

The reference has NO transformer and no tensor/sequence/expert
parallelism (SURVEY.md §2.4); this example is the new-capability
counterpart of `example/rnn/word_lm` showing the framework's flagship
SPMD stack end-to-end as a USER would drive it:

  * `TransformerConfig` + `create_mesh` choose the parallel layout
    (dp × tp × sp here; add pp/ep the same way),
  * `make_train_step(..., optimizer="adam")` returns ONE jitted step —
    ZeRO-1 sharded Adam, ring attention over "sp", Megatron col/row
    sharding over "tp", gradient psum over "dp" — with the shardings to
    place the data,
  * the loop just feeds globally-shaped [B, T] token batches.

Run (any host — the mesh is virtual CPU devices unless real chips
exist):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python train.py --steps 60

The task is next-char prediction on sequences of the form
"abcabcabc..." with a random phase/alphabet per sample — a tiny
dataset the model must actually learn (loss drops from ~ln(V) to near
0), so the example doubles as a convergence check.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_batch(rng, batch, seqlen, vocab, period=3):
    """Periodic sequences with random phase + offset; label = next char."""
    offs = rng.randint(0, vocab - period, size=(batch, 1))
    phase = rng.randint(0, period, size=(batch, 1))
    pos = np.arange(seqlen + 1)[None, :] + phase
    toks = (pos % period) + offs
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seqlen", type=int, default=32)
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=2)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--remat", default="none",
                    choices=("none", "dots", "dots_no_batch", "full"),
                    help="per-layer gradient checkpointing; 'full' is "
                         "what makes very long sequences (measured: "
                         "T=32k on one chip) trainable — see "
                         "benchmark/python/RESULTS_attention.md")
    ap.add_argument("--log-every", type=int, default=10)
    args = ap.parse_args()

    import jax

    from mxtpu.parallel import transformer as tf
    from mxtpu.parallel.mesh import (create_mesh, AXIS_DP, AXIS_PP,
                                     AXIS_TP, AXIS_SP, AXIS_EP)

    need = args.dp * args.tp * args.sp
    if len(jax.devices()) < need:
        raise SystemExit(
            "need %d devices (dp*tp*sp); run under JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=%d"
            % (need, need))

    cfg = tf.TransformerConfig(vocab=32, d_model=64, n_heads=4,
                               n_layers=2, d_ff=128,
                               max_len=args.seqlen, remat=args.remat)
    # size-1 axes stay in the mesh so every PartitionSpec resolves;
    # XLA elides collectives over singletons (grow pp/ep the same way)
    mesh = create_mesh({AXIS_DP: args.dp, AXIS_PP: 1, AXIS_TP: args.tp,
                        AXIS_SP: args.sp, AXIS_EP: 1})
    params = tf.init_params(cfg, mesh, seed=0)
    opt = tf.init_opt_state(cfg, mesh)
    step, shardings = tf.make_train_step(cfg, mesh, lr=args.lr,
                                         optimizer="adam")

    rng = np.random.RandomState(0)
    place = lambda x: jax.device_put(x, shardings["data"])
    first = last = None
    for it in range(args.steps):
        toks, labels = make_batch(rng, args.batch, args.seqlen,
                                  cfg.vocab)
        params, opt, loss = step(params, opt, place(toks), place(labels))
        loss = float(loss)
        first = loss if first is None else first
        last = loss
        if it % args.log_every == 0 or it == args.steps - 1:
            print("step %3d  loss %.4f" % (it, loss))
    print("first->last: %.4f -> %.4f" % (first, last))
    if last < first * 0.5:
        print("CONVERGED")
    else:
        raise SystemExit("did not converge")


if __name__ == "__main__":
    main()
