"""Neural style transfer, miniature.

Analog of the reference's `example/neural-style/`: optimize the INPUT
image so its conv features match a content image while its Gram
matrices match a style image (Gatys et al. 2015).  The distinctive
pattern here is gradient descent on pixels — `x.attach_grad()` plus a
manual Adam loop over the input, not the parameters.

Run:  python neural_style_mini.py [--steps 60]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd


class FeatureNet(gluon.nn.HybridBlock):
    """Small fixed (randomly-initialized) feature extractor — random
    conv features carry enough structure for toy style transfer."""

    def __init__(self):
        super().__init__()
        self.c1 = gluon.nn.Conv2D(8, 3, padding=1, activation="relu")
        self.c2 = gluon.nn.Conv2D(16, 3, padding=1, activation="relu")

    def hybrid_forward(self, F, x):
        f1 = self.c1(x)
        f2 = self.c2(F.Pooling(f1, kernel=(2, 2), stride=(2, 2),
                               pool_type="avg"))
        return f1, f2


def gram(f):
    n, c, h, w = f.shape
    m = f.reshape((n, c, h * w))
    return nd.batch_dot(m, m, transpose_b=True) / (c * h * w)


def make_images(size=32, seed=0):
    rng = np.random.RandomState(seed)
    yy, xx = np.mgrid[:size, :size] / size
    content = ((yy - 0.5) ** 2 + (xx - 0.5) ** 2 < 0.1) \
        .astype(np.float32)  # a disc
    style = np.sin(12 * np.pi * xx).astype(np.float32) * 0.5 + 0.5  # stripes
    c = np.stack([content] * 3)[None]
    s = np.stack([style, style * 0.5, 1 - style])[None]
    return c.astype(np.float32), s.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--style-weight", type=float, default=50.0)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    net = FeatureNet()
    net.initialize(mx.initializer.Xavier(), ctx=ctx)
    content_np, style_np = make_images()
    content = nd.array(content_np, ctx=ctx)
    style = nd.array(style_np, ctx=ctx)
    with autograd.pause():
        _, c2_t = net(content)               # content target (layer 2)
        s1, s2 = net(style)
        g1_t, g2_t = gram(s1), gram(s2)      # style targets

    # init from noise (the reference's --init random option): both the
    # content and style terms then have real distance to descend
    x = nd.array(np.random.RandomState(1)
                 .uniform(0.3, 0.7, content.shape).astype(np.float32),
                 ctx=ctx)
    x.attach_grad()
    # manual Adam on the pixels
    m = nd.zeros(x.shape, ctx=ctx)
    v = nd.zeros(x.shape, ctx=ctx)
    b1, b2, eps = 0.9, 0.999, 1e-8
    first = last = None
    for t in range(1, args.steps + 1):
        with autograd.record():
            f1, f2 = net(x)
            closs = ((f2 - c2_t) ** 2).mean()
            sloss = ((gram(f1) - g1_t) ** 2).mean() + \
                ((gram(f2) - g2_t) ** 2).mean()
            loss = closs + args.style_weight * sloss
        loss.backward()
        g = x.grad
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mh = m / (1 - b1 ** t)
        vh = v / (1 - b2 ** t)
        x = x - args.lr * mh / (vh.sqrt() + eps)
        x = nd.clip(x, 0.0, 1.0)
        x.attach_grad()
        last = float(loss.asnumpy())
        if first is None:
            first = last
        if t % 20 == 0:
            logging.info("step %d loss %.5f (content %.5f style %.5f)",
                         t, last, float(closs.asnumpy()),
                         float(sloss.asnumpy()))
    logging.info("loss %.5f -> %.5f", first, last)
    assert last < first * 0.7, "pixel optimization should reduce the loss"
    out = x.asnumpy()
    assert out.min() >= 0.0 and out.max() <= 1.0


if __name__ == "__main__":
    main()
