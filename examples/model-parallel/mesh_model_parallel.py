"""Model parallelism on a device mesh.

The reference's `example/model-parallel/` places layer groups on
different GPUs via `group2ctx` (`graph_executor.cc:1594`).  That style
of per-node placement does not map to XLA's compilation model — this
framework raises on multi-device group2ctx (`symbol/symbol.py`) and
does model parallelism the TPU way instead: shard the weight matrices
over a `Mesh` axis and let XLA insert the collectives
(`mxtpu.parallel`, Megatron column/row split).

This script runs a 2-layer MLP whose hidden dimension is split over
the `tp` axis: layer 1 column-parallel (no comm), layer 2 row-parallel
(ONE psum), exactly the Megatron-LM pattern.  On a host with no TPUs it
builds a virtual 8-device CPU mesh so the sharding is still exercised.

Run:  python mesh_model_parallel.py [--tp 4]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--tp", type=int, default=4)
    p.add_argument("--hidden", type=int, default=256)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)

    # virtual-CPU-mesh fallback (same guard as the test conftest):
    # jax_num_cpu_devices only exists on newer JAX; older builds take
    # the count from XLA_FLAGS, which must land before backend init
    n_dev = max(args.tp, 8)
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = (
            xla_flags +
            " --xla_force_host_platform_device_count=%d" % n_dev).strip()

    import jax

    if hasattr(jax.config, "jax_num_cpu_devices"):
        try:
            # a no-op error if backends are already initialized or a
            # real TPU mesh is present
            jax.config.update("jax_num_cpu_devices", n_dev)
        except RuntimeError:
            pass
    if len(jax.devices()) < args.tp:
        raise SystemExit("need >= %d devices for tp=%d (got %d); run "
                         "with more chips or a larger CPU mesh"
                         % (args.tp, args.tp, len(jax.devices())))
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mxtpu import parallel

    n_dev = len(jax.devices())
    mesh = parallel.create_mesh({"dp": n_dev // args.tp, "tp": args.tp})
    logging.info("mesh: %s", mesh)

    rng = np.random.RandomState(0)
    din, hidden, dout, batch = 64, args.hidden, 32, 128
    W1 = jnp.asarray(rng.normal(0, 0.05, (din, hidden)).astype(np.float32))
    W2 = jnp.asarray(rng.normal(0, 0.05, (hidden, dout)).astype(np.float32))
    Wt = jnp.asarray(rng.normal(0, 1.0, (din, dout)).astype(np.float32))
    X = jnp.asarray(rng.normal(0, 1, (batch, din)).astype(np.float32))
    Y = jnp.tanh(X @ Wt)

    # Megatron shardings: W1 column-split, W2 row-split over `tp`
    shard = {
        "W1": NamedSharding(mesh, P(None, "tp")),
        "W2": NamedSharding(mesh, P("tp", None)),
        "X": NamedSharding(mesh, P("dp", None)),
    }
    W1 = jax.device_put(W1, shard["W1"])
    W2 = jax.device_put(W2, shard["W2"])
    X = jax.device_put(X, shard["X"])

    def loss_fn(params, x, y):
        h = jnp.maximum(x @ params["W1"], 0)   # local: columns are split
        out = h @ params["W2"]                 # XLA inserts the psum here
        return jnp.mean((out - y) ** 2)

    @jax.jit
    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        return loss, {k: params[k] - 0.1 * grads[k] for k in params}

    params = {"W1": W1, "W2": W2}
    first = None
    for i in range(args.steps):
        loss, params = step(params, X, Y)
        if first is None:
            first = float(loss)
    logging.info("loss %.4f -> %.4f over %d steps (tp=%d)", first,
                 float(loss), args.steps, args.tp)
    # the weights stayed sharded through every step
    assert params["W1"].sharding.spec == P(None, "tp")
    assert float(loss) < first
    logging.info("per-device W1 shard shape: %s",
                 params["W1"].addressable_shards[0].data.shape)


if __name__ == "__main__":
    main()
