"""Faster R-CNN, miniature — the reference's `example/rcnn/` pipeline
end to end on a synthetic one-object detection task: a conv backbone,
an RPN head whose outputs feed `_contrib_Proposal` (anchor transform +
blocked greedy NMS), `ROIPooling` over the proposed regions, and a
Fast R-CNN head with joint softmax classification + smooth-L1 bbox
regression (reference `example/rcnn/symnet/symbol_resnet.py` roles).

Synthetic task: each 64x64 image contains one bright axis-aligned
square (class 1) or cross (class 2) on a noisy background; the model
must classify the ROI and refine its box.

Run:  python faster_rcnn_mini.py [--epochs 6]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

IMG = 64
FEAT_STRIDE = 8          # backbone downsamples 64 -> 8
NUM_CLASSES = 3          # background + {square, cross}


def make_batch(rng, n):
    """Images with one object each; returns images (n,1,64,64), class
    ids (n,), ground-truth boxes (n,4) in pixels."""
    imgs = rng.uniform(0, 0.25, (n, 1, IMG, IMG)).astype(np.float32)
    cls = rng.randint(1, NUM_CLASSES, n)
    boxes = np.zeros((n, 4), np.float32)
    for i in range(n):
        size = rng.randint(14, 26)
        x0 = rng.randint(2, IMG - size - 2)
        y0 = rng.randint(2, IMG - size - 2)
        if cls[i] == 1:   # filled square
            imgs[i, 0, y0:y0 + size, x0:x0 + size] = 1.0
        else:             # cross
            cx, cy = x0 + size // 2, y0 + size // 2
            imgs[i, 0, cy - 2:cy + 2, x0:x0 + size] = 1.0
            imgs[i, 0, y0:y0 + size, cx - 2:cx + 2] = 1.0
        boxes[i] = (x0, y0, x0 + size - 1, y0 + size - 1)
    return imgs, cls.astype(np.int64), boxes


class Backbone(gluon.nn.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.body = gluon.nn.HybridSequential()
            for ch in (16, 32, 32):   # three stride-2 stages: 64 -> 8
                self.body.add(gluon.nn.Conv2D(ch, 3, strides=2, padding=1,
                                              activation="relu"))

    def hybrid_forward(self, F, x):
        return self.body(x)


def iou_xyxy(a, b):
    ix0 = np.maximum(a[:, 0], b[:, 0])
    iy0 = np.maximum(a[:, 1], b[:, 1])
    ix1 = np.minimum(a[:, 2], b[:, 2])
    iy1 = np.minimum(a[:, 3], b[:, 3])
    inter = np.maximum(ix1 - ix0 + 1, 0) * np.maximum(iy1 - iy0 + 1, 0)
    area = lambda z: (z[:, 2] - z[:, 0] + 1) * (z[:, 3] - z[:, 1] + 1)
    return inter / (area(a) + area(b) - inter)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)
    ctx = mx.cpu()

    backbone = Backbone()
    # RPN head: objectness (2 per anchor) + box deltas (4 per anchor)
    n_anchor = 3
    rpn_conv = gluon.nn.Conv2D(32, 3, padding=1, activation="relu")
    rpn_cls = gluon.nn.Conv2D(2 * n_anchor, 1)
    rpn_reg = gluon.nn.Conv2D(4 * n_anchor, 1)
    # Fast R-CNN head over 4x4 pooled ROIs
    head = gluon.nn.HybridSequential()
    head.add(gluon.nn.Dense(64, activation="relu"))
    cls_fc = gluon.nn.Dense(NUM_CLASSES)
    box_fc = gluon.nn.Dense(4)
    blocks = [backbone, rpn_conv, rpn_cls, rpn_reg, head, cls_fc, box_fc]
    params = gluon.ParameterDict()
    for b in blocks:
        b.initialize(ctx=ctx)
        params.update(b.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": args.lr})

    prop_kw = dict(rpn_pre_nms_top_n=48, rpn_post_nms_top_n=4,
                   threshold=0.7, rpn_min_size=8,
                   scales=(2.0, 3.0, 4.0), ratios=(1.0,),
                   feature_stride=FEAT_STRIDE)
    im_info = nd.array(np.tile([IMG, IMG, 1.0],
                               (args.batch_size, 1)).astype(np.float32))

    for epoch in range(args.epochs):
        tot, correct, lsum = 0, 0, 0.0
        for _ in range(12):
            imgs, cls, gt = make_batch(rng, args.batch_size)
            x = nd.array(imgs)
            with autograd.record():
                feat = backbone(x)
                r = rpn_conv(feat)
                rpn_score = nd.softmax(
                    rpn_cls(r).reshape((0, 2, -1)), axis=1) \
                    .reshape((0, 2 * n_anchor,
                              IMG // FEAT_STRIDE, IMG // FEAT_STRIDE))
                rpn_delta = rpn_reg(r)
                # proposals ride the SAME graph (no grad through NMS,
                # matching the reference's Proposal op semantics)
                rois = nd.contrib.MultiProposal(
                    nd.BlockGrad(rpn_score), nd.BlockGrad(rpn_delta),
                    im_info, **prop_kw)
                pooled = nd.ROIPooling(feat, rois, pooled_size=(4, 4),
                                       spatial_scale=1.0 / FEAT_STRIDE)
                h = head(pooled.reshape((pooled.shape[0], -1)))
                logits = cls_fc(h)
                deltas = box_fc(h)
                # assign each ROI the image-level target (one object)
                rois_np = rois.asnumpy()
                img_idx = rois_np[:, 0].astype(int)
                labels = nd.array(cls[img_idx])
                g = gt[img_idx]
                rb = rois_np[:, 1:]
                # degenerate proposals (x1<x0 after clipping) would put
                # NaN into the targets — and NaN*0 defeats the pos mask
                rw = np.maximum(rb[:, 2] - rb[:, 0] + 1.0, 1.0)
                rh = np.maximum(rb[:, 3] - rb[:, 1] + 1.0, 1.0)
                tgt = np.stack(
                    [((g[:, 0] + g[:, 2]) - (rb[:, 0] + rb[:, 2])) / 2 / rw,
                     ((g[:, 1] + g[:, 3]) - (rb[:, 1] + rb[:, 3])) / 2 / rh,
                     np.log((g[:, 2] - g[:, 0] + 1) / rw),
                     np.log((g[:, 3] - g[:, 1] + 1) / rh)], 1)
                tgt = np.clip(tgt, -4.0, 4.0).astype(np.float32)
                # only ROIs overlapping the object learn the box
                pos = (iou_xyxy(rb, g) > 0.3).astype(np.float32)[:, None]
                ce = nd.softmax_cross_entropy(logits, labels) / labels.shape[0]
                sl1 = (nd.smooth_l1(deltas - nd.array(tgt), scalar=1.0) *
                       nd.array(pos)).mean()
                loss = ce + sl1
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
            pred = logits.asnumpy().argmax(1)
            correct += int((pred == cls[img_idx]).sum())
            tot += len(img_idx)
        acc = correct / max(tot, 1)
        logging.info("epoch %d rcnn loss %.4f roi accuracy %.3f",
                     epoch, lsum / 12, acc)
    print("FINAL_ROI_ACCURACY %.4f" % acc)


if __name__ == "__main__":
    main()
