"""Captcha recognition — the reference's `example/captcha/` role
(multi-digit recognition with a multi-head CNN): render 4-digit codes
as 7-segment glyph strips with noise/jitter, one softmax head per
position, joint training, exact-match evaluation.

Run:  python captcha_cnn.py [--epochs 10]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

N_DIGIT = 4
H, W = 20, 56          # 4 glyphs of 14px

# 7-segment truth table (a b c d e f g) per digit
SEGS = {0: "abcdef", 1: "bc", 2: "abged", 3: "abgcd", 4: "fgbc",
        5: "afgcd", 6: "afgedc", 7: "abc", 8: "abcdefg", 9: "abcfgd"}


def render_digit(img, x0, d, rng):
    y0 = rng.randint(0, 4)
    seg = SEGS[d]
    t = 2
    if "a" in seg:
        img[y0:y0 + t, x0 + 2:x0 + 10] = 1
    if "g" in seg:
        img[y0 + 6:y0 + 6 + t, x0 + 2:x0 + 10] = 1
    if "d" in seg:
        img[y0 + 12:y0 + 12 + t, x0 + 2:x0 + 10] = 1
    if "f" in seg:
        img[y0:y0 + 8, x0 + 2:x0 + 2 + t] = 1
    if "b" in seg:
        img[y0:y0 + 8, x0 + 8:x0 + 8 + t] = 1
    if "e" in seg:
        img[y0 + 6:y0 + 14, x0 + 2:x0 + 2 + t] = 1
    if "c" in seg:
        img[y0 + 6:y0 + 14, x0 + 8:x0 + 8 + t] = 1


def make_batch(rng, n):
    xs = rng.uniform(0, 0.3, (n, 1, H, W)).astype(np.float32)
    ys = rng.randint(0, 10, (n, N_DIGIT))
    for i in range(n):
        for j in range(N_DIGIT):
            render_digit(xs[i, 0], j * 14 + rng.randint(0, 3),
                         ys[i, j], rng)
    return xs, ys.astype(np.float32)


class CaptchaNet(gluon.nn.HybridBlock):
    def __init__(self, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.features = gluon.nn.HybridSequential()
            self.features.add(
                gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                gluon.nn.MaxPool2D(2),
                gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
                gluon.nn.MaxPool2D(2),
                gluon.nn.Dense(128, activation="relu"))
            self.heads = [gluon.nn.Dense(10, prefix="head%d_" % i)
                          for i in range(N_DIGIT)]
            for h in self.heads:
                self.register_child(h)

    def hybrid_forward(self, F, x):
        h = self.features(x)
        return nd.stack(*[head(h) for head in self.heads],
                        axis=1)  # (B, N_DIGIT, 10)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=4)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    net = CaptchaNet()
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        lsum = 0.0
        for _ in range(15):
            x, y = make_batch(rng, args.batch_size)
            with autograd.record():
                logits = net(nd.array(x))
                loss = loss_fn(logits.reshape((-1, 10)),
                               nd.array(y.reshape(-1))).mean()
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
        x, y = make_batch(rng, 128)
        pred = net(nd.array(x)).asnumpy().argmax(-1)
        digit_acc = float((pred == y).mean())
        exact = float((pred == y).all(axis=1).mean())
        logging.info("epoch %d loss %.4f digit acc %.3f exact %.3f",
                     epoch, lsum / 15, digit_acc, exact)
    print("FINAL_DIGIT_ACCURACY %.4f" % digit_acc)


if __name__ == "__main__":
    main()
