"""Multi-task training with a grouped Symbol.

Analog of the reference's `example/multi-task/`: one shared trunk, two
SoftmaxOutput heads (the digit class and a parity task), bound as a
`sym.Group` through Module — the whole two-head step is still ONE fused
XLA program.  Shows a custom multi-output metric.

Run:  python multitask_mnist.py [--epochs 5]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import sym


def build_net():
    data = sym.Variable("data")
    h = sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = sym.Activation(h, act_type="relu")
    cls = sym.FullyConnected(h, num_hidden=10, name="fc_class")
    cls = sym.SoftmaxOutput(cls, sym.Variable("class_label"),
                            name="softmax_class")
    par = sym.FullyConnected(h, num_hidden=2, name="fc_parity")
    par = sym.SoftmaxOutput(par, sym.Variable("parity_label"),
                            name="softmax_parity")
    return sym.Group([cls, par])


class MultiAccuracy(mx.metric.EvalMetric):
    """Per-head accuracy (reference example's Multi_Accuracy)."""

    def __init__(self, num=2):
        self.num = num
        super().__init__("multi-accuracy")

    def reset(self):
        self.num_inst = [0] * self.num
        self.sum_metric = [0.0] * self.num

    def update(self, labels, preds):
        for i in range(self.num):
            pred = np.argmax(preds[i].asnumpy(), axis=1)
            label = labels[i].asnumpy().astype(np.int64)
            self.sum_metric[i] += (pred == label).sum()
            self.num_inst[i] += len(label)

    def get(self):
        accs = [s / max(n, 1) for s, n in zip(self.sum_metric,
                                              self.num_inst)]
        return ["class-acc", "parity-acc"], accs

    def get_name_value(self):
        names, values = self.get()
        return list(zip(names, values))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(0)
    np.random.seed(0)

    rng = np.random.RandomState(0)
    templates = rng.uniform(0, 1, (10, 64)).astype(np.float32)
    y = rng.randint(0, 10, 2048)
    X = templates[y] + rng.normal(0, 0.1, (2048, 64)).astype(np.float32)
    it = mx.io.NDArrayIter(
        X, {"class_label": y.astype(np.float32),
            "parity_label": (y % 2).astype(np.float32)},
        batch_size=args.batch_size, shuffle=True)

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    mod = mx.mod.Module(build_net(), context=ctx,
                        label_names=("class_label", "parity_label"))
    metric = MultiAccuracy()
    mod.fit(it, num_epoch=args.epochs, optimizer="adam",
            optimizer_params={"learning_rate": 1e-3},
            eval_metric=metric,
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 20))
    it.reset()
    metric.reset()
    mod.score(it, metric)
    names, accs = metric.get()
    for n, a in zip(names, accs):
        logging.info("%s = %.3f", n, a)
    assert all(a > 0.9 for a in accs), accs


if __name__ == "__main__":
    main()
