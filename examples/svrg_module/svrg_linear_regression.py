"""SVRG vs plain SGD on linear regression.

Analog of the reference's `example/svrg_module/`: the same model
trained twice — plain-SGD Module vs SVRGModule — showing the
variance-reduced path tolerating a larger constant learning rate.

Run:  python svrg_linear_regression.py [--epochs 30]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu.contrib.svrg_optimization import SVRGModule


def build(dim):
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=1, no_bias=True,
                                name="fc")
    return mx.sym.LinearRegressionOutput(
        out, mx.sym.Variable("lin_label"), name="lro")


def make_data(n=512, dim=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.uniform(-1, 1, (n, dim)).astype(np.float32)
    w = np.linspace(1, 2, dim).astype(np.float32)
    Y = X @ w + rng.normal(0, 0.01, n).astype(np.float32)
    return X, Y.reshape(-1, 1), w


def final_mse(mod, it):
    m = mx.metric.MSE()
    it.reset()
    mod.score(it, m)
    return m.get()[1]


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.2)
    p.add_argument("--update-freq", type=int, default=2)
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    np.random.seed(0)

    X, Y, true_w = make_data()
    net = build(X.shape[1])

    def iter_():
        return mx.io.NDArrayIter(X, Y, batch_size=args.batch_size,
                                 shuffle=True, label_name="lin_label")

    sgd_mod = mx.mod.Module(net, context=mx.cpu(),
                            label_names=("lin_label",))
    it = iter_()
    sgd_mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
                eval_metric="mse",
                optimizer_params={"learning_rate": args.lr})
    sgd_mse = final_mse(sgd_mod, it)

    svrg_mod = SVRGModule(net, context=mx.cpu(),
                          label_names=("lin_label",),
                          update_freq=args.update_freq)
    it = iter_()
    svrg_mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
                 eval_metric="mse",
                 optimizer_params={"learning_rate": args.lr})
    svrg_mse = final_mse(svrg_mod, it)

    w_est = svrg_mod.get_params()[0]["fc_weight"].asnumpy().ravel()
    logging.info("plain SGD final MSE:  %.6f", sgd_mse)
    logging.info("SVRG final MSE:       %.6f", svrg_mse)
    logging.info("SVRG weight error:    %.4f",
                 float(np.abs(w_est - true_w).max()))
    assert svrg_mse < 0.05, "SVRG should recover the planted model"


if __name__ == "__main__":
    main()
