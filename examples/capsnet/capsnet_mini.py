"""CapsNet, miniature — the reference's `example/capsnet/` role:
capsule layers with dynamic routing-by-agreement (Sabour et al. 2017)
and margin loss, TPU-first: the routing iterations are a fixed-trip
einsum loop (static shapes, MXU-friendly), not per-capsule scalar work.

Synthetic task: 20x20 images of 3 shape classes (square / cross /
diagonal stripes) with jitter — pose-varying inputs, which is the
regime capsules are built for.

Run:  python capsnet_mini.py [--epochs 8]
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import argparse
import logging

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon, nd

IMG = 20
N_CLASS = 3


def make_batch(rng, n):
    xs = rng.uniform(0, 0.2, (n, 1, IMG, IMG)).astype(np.float32)
    ys = rng.randint(0, N_CLASS, n)
    for i in range(n):
        x0, y0 = rng.randint(2, 8, 2)
        s = rng.randint(8, 11)
        if ys[i] == 0:
            xs[i, 0, y0:y0 + s, x0:x0 + s] = 1.0
            xs[i, 0, y0 + 2:y0 + s - 2, x0 + 2:x0 + s - 2] = 0.2
        elif ys[i] == 1:
            c = s // 2
            xs[i, 0, y0 + c - 1:y0 + c + 1, x0:x0 + s] = 1.0
            xs[i, 0, y0:y0 + s, x0 + c - 1:x0 + c + 1] = 1.0
        else:
            for d in range(s):
                xs[i, 0, y0 + d, x0 + d] = 1.0
                if d + 3 < s:
                    xs[i, 0, y0 + d + 3, x0 + d] = 1.0
    return xs, ys.astype(np.float32)


def squash(v, axis=-1):
    n2 = (v ** 2).sum(axis=axis, keepdims=True)
    return v * (n2 / (1.0 + n2)) / nd.sqrt(n2 + 1e-9)


class CapsNet(gluon.nn.HybridBlock):
    """conv -> primary caps (8D) -> routed class caps (16D)."""

    def __init__(self, n_routing=2, **kw):
        super().__init__(**kw)
        self.n_routing = n_routing
        with self.name_scope():
            self.conv = gluon.nn.Conv2D(32, 5, strides=2,
                                        activation="relu")
            self.primary = gluon.nn.Conv2D(32, 3, strides=2)  # 4 caps x 8D
            # 20x20 -> conv5/2 -> 8x8 -> conv3/2 -> 3x3; 32ch = 4 caps
            # of 8D per position -> P = 4*3*3 = 36 primary capsules
            # W: (P, N_CLASS, 16, 8) prediction transform
            self.W = self.params.get(
                "routing_weight", shape=(4 * 3 * 3, N_CLASS, 16, 8),
                init=mx.init.Xavier())

    def hybrid_forward(self, F, x, W):
        h = self.primary(self.conv(x))          # (B, 32, 3, 3)
        B, _, hh, ww = h.shape
        u = squash(h.reshape((B, 4, 8, hh, ww))
                   .transpose((0, 1, 3, 4, 2)).reshape((B, -1, 8)))
        # prediction vectors u_hat: (B, P, C, 16)
        u_hat = nd.einsum(u, W, subscripts="bpi,pcoi->bpco")
        b_logit = nd.zeros((B, u_hat.shape[1], N_CLASS), ctx=x.ctx)
        for r in range(self.n_routing):
            c = nd.softmax(b_logit, axis=2)          # route weights
            s = nd.einsum(c, u_hat, subscripts="bpc,bpco->bco")
            v = squash(s)                            # (B, C, 16)
            if r < self.n_routing - 1:
                b_logit = b_logit + nd.einsum(
                    u_hat, v, subscripts="bpco,bco->bpc")
        return nd.sqrt((v ** 2).sum(axis=-1) + 1e-9)  # class lengths


def margin_loss(lengths, y):
    """reference capsnet margin loss: m+ = 0.9, m- = 0.1, lam = 0.5."""
    onehot = nd.one_hot(y, depth=N_CLASS)
    pos = nd.relu(0.9 - lengths) ** 2
    neg = nd.relu(lengths - 0.1) ** 2
    return (onehot * pos + 0.5 * (1 - onehot) * neg).sum(axis=1).mean()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=9)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    net = CapsNet()
    net.initialize(ctx=mx.cpu())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    for epoch in range(args.epochs):
        lsum = 0.0
        for _ in range(15):
            x, y = make_batch(rng, args.batch_size)
            with autograd.record():
                lengths = net(nd.array(x))
                loss = margin_loss(lengths, nd.array(y))
            loss.backward()
            trainer.step(1)
            lsum += float(loss.asnumpy())
        x, y = make_batch(rng, 128)
        acc = float((net(nd.array(x)).asnumpy().argmax(1) == y).mean())
        logging.info("epoch %d margin loss %.4f accuracy %.3f",
                     epoch, lsum / 15, acc)
    print("FINAL_ACCURACY %.4f" % acc)


if __name__ == "__main__":
    main()
