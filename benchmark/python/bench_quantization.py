"""INT8 vs fp32 inference micro-benchmark (reference
`benchmark/python/quantization/benchmark_op.py`).

Usage: python benchmark/python/bench_quantization.py
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import nd


def bench_fc(batch, in_dim, out_dim, iters):
    rng = np.random.RandomState(0)
    x = nd.array(rng.uniform(-1, 1, (batch, in_dim)).astype(np.float32))
    w = nd.array(rng.uniform(-1, 1, (out_dim, in_dim))
                 .astype(np.float32))
    b = nd.array(np.zeros(out_dim, np.float32))

    def run_fp32():
        return nd.FullyConnected(x, w, b, num_hidden=out_dim)

    qx = nd.contrib.quantize_v2(x)
    qw = nd.contrib.quantize_v2(w)
    qb = nd.array(np.zeros(out_dim, np.int8))

    def run_int8():
        return nd.contrib.quantized_fully_connected(
            qx[0], qw[0], qb, qx[1], qx[2], qw[1], qw[2],
            qw[1], qw[2], num_hidden=out_dim)

    rates = {}
    for fn, name in ((run_fp32, "fp32"), (run_int8, "int8")):
        fn()[0].wait_to_read()
        tic = time.perf_counter()
        for _ in range(iters):
            out = fn()
        out[0].wait_to_read()
        rate = iters / (time.perf_counter() - tic)
        rates[name] = rate
        print("FC %dx%d->%d  %s: %9.1f it/s"
              % (batch, in_dim, out_dim, name, rate), file=sys.stderr)
    return rates


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=30)
    args = p.parse_args()
    rows = {
        "fc_64x1024": bench_fc(64, 1024, 1024, args.iters),
        "fc_32x4096": bench_fc(32, 4096, 4096, max(args.iters // 3, 5)),
    }
    # structured row (shared runner schema): int8-vs-fp32 speedup on
    # the large FC — the config quantized serving actually runs
    import bench_common

    big = rows["fc_32x4096"]
    bench_common.emit_result(
        "quantization", "quantized_fc_int8_speedup",
        round(big["int8"] / big["fp32"], 3) if big.get("fp32") else 0.0,
        "x",
        throughput=big.get("int8"),
        step_time_us=(1e6 / big["int8"]) if big.get("int8") else None,
        extra={k: {n: round(v, 1) for n, v in r.items()}
               for k, r in rows.items()})


if __name__ == "__main__":
    main()
