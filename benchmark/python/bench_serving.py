#!/usr/bin/env python
"""Closed-loop serving load generator: throughput AT a p99 budget.

Raw tok/s (or img/s) is the wrong serving metric — a server that
doubles throughput by letting p99 run away is worse, not better.  This
bench reports what the ROADMAP's serving axis asks for: the highest
SUSTAINED throughput whose client-observed p99 stays inside
``--p99-budget-ms``, found by ramping closed-loop concurrency
(1, 2, 4, ... up to ``--max-concurrency``) and holding each stage for
``--duration`` seconds.  Closed-loop: each client issues its next
request only after the previous one returns, so offered load tracks
delivered load and the queue cannot run away on its own.

Two targets:

  * in-process (default): an `mx.serve.Server` hosting a bucket-warmed
    MLP, driven through `Server.infer` — measures the micro-batcher +
    compiled-program stack without HTTP overhead;
  * ``--endpoints host:port,...``: a live replica fleet via the
    failover `mx.serve.Client` — measures the full wire path
    (what `tools/check_serving.py` chaos-tests).

Latency comes from `telemetry.Histogram` (one fresh histogram per
stage — the same primitive the server's own SLO layer uses), and each
stage also reports the server-side batch-occupancy and queue-depth
gauges from ``mx.telemetry.metrics()``.

Example::

    python benchmark/python/bench_serving.py --p99-budget-ms 100
    python benchmark/python/bench_serving.py \\
        --endpoints 127.0.0.1:8080,127.0.0.1:8081 --json out.json
"""
import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, ROOT)

SAMPLE = (32,)


def build_model(width=64):
    import mxtpu as mx
    from mxtpu.gluon import nn

    mx.random.seed(3)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(width, activation="relu"),
                nn.Dense(width, activation="relu"), nn.Dense(8))
    net.initialize(mx.initializer.Xavier(rnd_type="uniform"))
    net.hybridize()
    return net


def run_stage(predict, concurrency, duration, max_rows, hist):
    """One closed-loop stage: ``concurrency`` clients, each issuing
    its next request only after the last returned.  Returns
    (requests, rows, errors, wall_s)."""
    import numpy as np

    stop = time.monotonic() + duration
    counts = [0] * concurrency
    rows = [0] * concurrency
    errors = [0] * concurrency

    def client(i):
        rng = np.random.RandomState(100 + i)
        while time.monotonic() < stop:
            n = int(rng.randint(1, max_rows + 1))
            x = rng.rand(n, *SAMPLE).astype("float32")
            t0 = time.monotonic()
            try:
                predict(x)
            except Exception:
                errors[i] += 1
                continue
            hist.record(time.monotonic() - t0)
            counts[i] += 1
            rows[i] += n

    t0 = time.monotonic()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(counts), sum(rows), sum(errors), time.monotonic() - t0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--duration", type=float, default=5.0,
                    help="seconds per concurrency stage")
    ap.add_argument("--max-concurrency", type=int, default=16)
    ap.add_argument("--max-rows", type=int, default=4,
                    help="max rows per request (ragged 1..N)")
    ap.add_argument("--p99-budget-ms", type=float, default=200.0)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--width", type=int, default=64)
    ap.add_argument("--endpoints", default=None,
                    help="host:port,... — drive a live fleet instead "
                         "of an in-process server")
    ap.add_argument("--model", default="mlp",
                    help="model name on the fleet (--endpoints mode)")
    ap.add_argument("--json", default=None, help="write results here")
    args = ap.parse_args()

    import mxtpu as mx
    from mxtpu import telemetry

    server = None
    if args.endpoints:
        eps = [e.strip() for e in args.endpoints.split(",") if e.strip()]
        assert mx.serve.wait_ready(eps, 60), "fleet not ready"
        client = mx.serve.Client(eps)
        model = args.model

        def predict(x):
            return client.predict(model, x)
        target = "fleet %s" % eps
    else:
        server = mx.serve.Server(max_batch=args.max_batch)
        server.add_model("mlp", build_model(args.width),
                         input_shape=SAMPLE)
        server.start()

        def predict(x):
            return server.infer("mlp", x)
        target = "in-process server (max_batch=%d, buckets warmed)" \
            % args.max_batch

    print("bench_serving: closed-loop ramp against %s" % target)
    print("stage  conc   req/s   rows/s  p50ms  p95ms  p99ms  "
          "occup%  qdepth  ok")
    stages = []
    sustained = None
    c = 1
    while c <= args.max_concurrency:
        hist = telemetry.Histogram(low=1e-5, high=1e3)
        nreq, nrows, nerr, wall = run_stage(
            predict, c, args.duration, args.max_rows, hist)
        snap = hist.snapshot()
        m = telemetry.metrics().get("serve", {})
        stage = {
            "concurrency": c,
            "requests_per_s": nreq / wall,
            "rows_per_s": nrows / wall,
            "errors": nerr,
            "p50_ms": snap["p50"] * 1e3,
            "p95_ms": snap["p95"] * 1e3,
            "p99_ms": snap["p99"] * 1e3,
            "batch_occupancy_pct": m.get("batch_occupancy_pct", -1),
            "queue_depth": m.get("queue_depth", -1),
        }
        stages.append(stage)
        within = snap["p99"] * 1e3 <= args.p99_budget_ms and nerr == 0
        print("%5d %5d %7.1f %8.1f %6.1f %6.1f %6.1f %7.1f %7d  %s"
              % (c, c, stage["requests_per_s"], stage["rows_per_s"],
                 stage["p50_ms"], stage["p95_ms"], stage["p99_ms"],
                 stage["batch_occupancy_pct"], stage["queue_depth"],
                 "yes" if within else "NO"))
        if within:
            if sustained is None or stage["rows_per_s"] > \
                    sustained["rows_per_s"]:
                sustained = stage
        else:
            break  # past the knee: higher concurrency only gets worse
        c *= 2

    if sustained:
        print("bench_serving: SUSTAINED %.1f rows/s (%.1f req/s) at "
              "p99 %.1fms within the %.0fms budget (concurrency %d)"
              % (sustained["rows_per_s"], sustained["requests_per_s"],
                 sustained["p99_ms"], args.p99_budget_ms,
                 sustained["concurrency"]))
    else:
        print("bench_serving: NO stage met the %.0fms p99 budget"
              % args.p99_budget_ms)

    if server is not None:
        server.close()
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"p99_budget_ms": args.p99_budget_ms,
                       "sample_shape": SAMPLE,
                       "stages": stages,
                       "sustained": sustained}, f, indent=2)
        print("bench_serving: wrote %s" % args.json)
    import bench_common

    bench_common.emit_result(
        "serving", "serving_sustained_rows_per_s_at_p99",
        round(sustained["rows_per_s"], 1) if sustained else 0.0,
        "rows/s",
        throughput=sustained["rows_per_s"] if sustained else 0.0,
        step_time_us=(sustained["p99_ms"] * 1e3) if sustained else None,
        extra={"p99_budget_ms": args.p99_budget_ms,
               "sustained": sustained, "stages": stages,
               "target": target})
    return 0 if sustained else 1


if __name__ == "__main__":
    sys.exit(main())
