"""Gluon layer micro-benchmarks (reference
`benchmark/python/gluon/benchmark_gluon.py`): forward / forward+backward
images-per-second for model-zoo nets at several batch sizes.

Usage: python benchmark/python/bench_gluon.py [--networks resnet18_v1]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import autograd, gluon
from mxtpu.gluon.model_zoo import vision


def bench(name, batch, train, iters, ctx):
    net = getattr(vision, name)(classes=1000)
    net.initialize(ctx=ctx)
    x = mx.nd.array(np.random.uniform(size=(batch, 3, 224, 224))
                    .astype(np.float32), ctx=ctx)
    net(x)
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    y = mx.nd.array(np.zeros(batch, np.float32), ctx=ctx)

    def step():
        if train:
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            return loss
        return net(x)

    step().wait_to_read()
    tic = time.perf_counter()
    out = None
    for _ in range(iters):
        out = step()
    out.wait_to_read()
    return batch * iters / (time.perf_counter() - tic)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--networks", default="resnet18_v1,mobilenet1_0")
    p.add_argument("--batch-sizes", default="1,32")
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()
    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    print("device:", ctx)
    for name in args.networks.split(","):
        for bs in (int(b) for b in args.batch_sizes.split(",")):
            fwd = bench(name, bs, False, args.iters, ctx)
            bwd = bench(name, bs, True, args.iters, ctx)
            print("%-16s bs=%-3d  fwd %9.1f img/s   fwd+bwd %9.1f img/s"
                  % (name, bs, fwd, bwd))


if __name__ == "__main__":
    main()
