#!/usr/bin/env python
"""Flash-attention kernel microbenchmark (Pallas vs fused-jnp reference).

The reference framework composes attention from batch_dot + softmax,
materializing the (T, T) score matrix (`src/operator/tensor/dot.cc` +
`softmax.cc` composition); this framework ships a Pallas flash kernel
(`mxtpu/ops/pallas_attention.py`) with online-softmax forward and
blocked-recompute backward. This benchmark times both paths on the
current backend over a sequence-length sweep, forward and
forward+backward, and prints one JSON line per (path, seq, mode).

Safe-by-construction for the axon tunnel: shapes start tiny and grow,
every config is try/except'd (an OOM or lowering failure skips, never
kills the process mid-op), and there is no external timeout to SIGTERM
the run — see BENCH_NOTES_r05.md on tunnel wedging.

Usage:  python benchmark/python/bench_attention.py            # on chip
        JAX_PLATFORMS=cpu python benchmark/python/bench_attention.py \
            --seqs 256,512 --iters 2   # CPU smoke
"""
import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def attn_flops(b, h, t, d, causal, bwd):
    """2*T^2*d MACs for QK^T plus the same for PV -> 4*T^2*d FLOPs/head
    forward; backward recomputes scores and adds dq/dk/dv matmuls
    (~2.5x forward); causal halves the useful work."""
    f = 4.0 * b * h * t * t * d
    if causal:
        f *= 0.5
    return f * (3.5 if bwd else 1.0)


def run(fn, args, iters):
    import jax

    out = fn(*args)                      # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--seqs", default="512,1024,2048,4096,8192")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--causal", action="store_true", default=True)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mxtpu.ops import pallas_attention as pa

    b, h, d = args.batch, args.heads, args.head_dim
    dt = jnp.dtype(args.dtype)

    for t in [int(s) for s in args.seqs.split(",") if s]:
        rng = np.random.RandomState(t)
        q = jnp.asarray(rng.randn(b * h, t, d), dtype=dt)
        k = jnp.asarray(rng.randn(b * h, t, d), dtype=dt)
        v = jnp.asarray(rng.randn(b * h, t, d), dtype=dt)
        sm = 1.0 / float(np.sqrt(d))

        paths = {}
        if pa._use_pallas():
            # flash_attention's routing is automatic on this backend
            paths["pallas_flash"] = functools.partial(
                pa.flash_attention, causal=args.causal)
        ref = functools.partial(pa._reference_attention,
                                sm_scale=sm, causal=args.causal)
        paths["jnp_materialized"] = lambda q, k, v: ref(q, k, v)

        for name, fn in paths.items():
            try:
                fwd = jax.jit(fn)

                def loss(q, k, v, _fn=fn):
                    return _fn(q, k, v).astype(jnp.float32).sum()

                fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                t_f = run(fwd, (q, k, v), args.iters)
                t_b = run(fwdbwd, (q, k, v), args.iters)
                for mode, tt in (("fwd", t_f), ("fwd+bwd", t_b)):
                    fl = attn_flops(1, b * h, t, d, args.causal,
                                    mode != "fwd")
                    print(json.dumps({
                        "path": name, "seq": t, "mode": mode,
                        "dtype": args.dtype, "causal": args.causal,
                        "ms": round(tt * 1e3, 3),
                        "tflops": round(fl / tt / 1e12, 2),
                    }))
            except Exception as e:
                print(json.dumps({"path": name, "seq": t,
                                  "error": str(e)[:300]}))


if __name__ == "__main__":
    main()
