#!/usr/bin/env python
"""Flash-attention kernel microbenchmark (Pallas vs fused-jnp reference).

The reference framework composes attention from batch_dot + softmax,
materializing the (T, T) score matrix (`src/operator/tensor/dot.cc` +
`softmax.cc` composition); this framework ships a Pallas flash kernel
(`mxtpu/ops/pallas_attention.py`) with online-softmax forward and
blocked-recompute backward. This benchmark times both paths on the
current backend over a sequence-length sweep, forward and
forward+backward, and prints one JSON line per (path, seq, mode).

Safe-by-construction for the axon tunnel: shapes start tiny and grow,
every config is try/except'd (an OOM or lowering failure skips, never
kills the process mid-op), and there is no external timeout to SIGTERM
the run — see BENCH_NOTES_r05.md on tunnel wedging.

Usage:  python benchmark/python/bench_attention.py            # on chip
        JAX_PLATFORMS=cpu python benchmark/python/bench_attention.py \
            --seqs 256,512 --iters 2   # CPU smoke
"""
import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def attn_flops(b, h, t, d, causal, bwd):
    """2*T^2*d MACs for QK^T plus the same for PV -> 4*T^2*d FLOPs/head
    forward; backward recomputes scores and adds dq/dk/dv matmuls
    (~2.5x forward); causal halves the useful work."""
    f = 4.0 * b * h * t * t * d
    if causal:
        f *= 0.5
    return f * (3.5 if bwd else 1.0)


def _value_sync(out):
    """True data-dependency sync: fetch one element of every output
    leaf.  Buffer-readiness events through the tunneled runtime are
    unreliable after a pallas execution (measured r5s3 — they report
    ready before the program finishes; see BENCH_NOTES_r05.md), so
    block_until_ready is NOT a valid timing fence here; a value fetch
    is, because the bytes must come from the finished computation."""
    import jax
    import jax.numpy as jnp

    for leaf in jax.tree_util.tree_leaves(out):
        float(jnp.ravel(leaf)[0])


def run(fn, args, iters, min_window_s=0.5, max_iters=1000):
    """Differential timing: close a K-iteration and a 2K-iteration
    window with the same value fetch; (t_2K - t_K)/K cancels both the
    fetch's host round-trip and any constant per-window overhead.
    Device programs execute in dispatch order, so fetching the last
    output's value drains the whole window.

    K auto-scales from a pilot window so the differential stays well
    above the tunnel's RTT jitter (~10 ms) — with fast kernels a
    fixed K makes (t_2K - t_K) - (t_K - t_0) pure noise (first fixed
    run printed 0.0 ms / 1.5e8 TFLOPS rows for the short sequences)."""
    out = fn(*args)                      # compile
    _value_sync(out)
    # fetch round-trip on an already-computed result: deducted from the
    # pilot so K is sized by actual per-iter device time, not RTT
    t0 = time.perf_counter()
    _value_sync(out)
    rtt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _value_sync(out)
    pilot = max((time.perf_counter() - t0) - rtt, 1e-6 * iters) / iters
    k = int(min(max(iters, min_window_s / max(pilot, 1e-7)), max_iters))
    t0 = time.perf_counter()
    for _ in range(k):
        out = fn(*args)
    _value_sync(out)
    t1 = time.perf_counter()
    for _ in range(2 * k):
        out = fn(*args)
    _value_sync(out)
    t2 = time.perf_counter()
    diff = (t2 - t1) - (t1 - t0)
    if diff <= 0:
        # window smaller than the RTT jitter even at max_iters: there
        # is no honest number here — report it as such rather than
        # flooring to an absurd TFLOPS row
        return float("nan")
    return diff / k


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--seqs", default="512,1024,2048,4096,8192")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--causal", action="store_true", default=True)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from mxtpu.ops import pallas_attention as pa

    b, h, d = args.batch, args.heads, args.head_dim
    dt = jnp.dtype(args.dtype)

    for t in [int(s) for s in args.seqs.split(",") if s]:
        rng = np.random.RandomState(t)
        q = jnp.asarray(rng.randn(b * h, t, d), dtype=dt)
        k = jnp.asarray(rng.randn(b * h, t, d), dtype=dt)
        v = jnp.asarray(rng.randn(b * h, t, d), dtype=dt)
        sm = 1.0 / float(np.sqrt(d))

        paths = {}
        if pa._use_pallas():
            # flash_attention's routing is automatic on this backend
            paths["pallas_flash"] = functools.partial(
                pa.flash_attention, causal=args.causal)
        ref = functools.partial(pa._reference_attention,
                                sm_scale=sm, causal=args.causal)
        paths["jnp_materialized"] = lambda q, k, v: ref(q, k, v)

        for name, fn in paths.items():
            try:
                fwd = jax.jit(fn)

                def loss(q, k, v, _fn=fn):
                    return _fn(q, k, v).astype(jnp.float32).sum()

                fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
                t_f = run(fwd, (q, k, v), args.iters)
                t_b = run(fwdbwd, (q, k, v), args.iters)
                for mode, tt in (("fwd", t_f), ("fwd+bwd", t_b)):
                    if tt != tt:       # NaN: noise-dominated window
                        print(json.dumps({
                            "path": name, "seq": t, "mode": mode,
                            "error": "window below RTT jitter even at "
                                     "max_iters; no honest number"}))
                        continue
                    fl = attn_flops(1, b * h, t, d, args.causal,
                                    mode != "fwd")
                    print(json.dumps({
                        "path": name, "seq": t, "mode": mode,
                        "dtype": args.dtype, "causal": args.causal,
                        "ms": round(tt * 1e3, 3),
                        "tflops": round(fl / tt / 1e12, 2),
                    }))
            except Exception as e:
                print(json.dumps({"path": name, "seq": t,
                                  "error": str(e)[:300]}))


if __name__ == "__main__":
    main()
