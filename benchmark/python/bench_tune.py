"""`mx.tune` benchmark seed: tuned-vs-default step time for one real
measured-trial search session.

What the autotuner buys (ROADMAP item 2 direction): the knob space
(donation, pass pipeline, steps-per-program batching, ...) is searched
with REAL subprocess trials instead of hand-tuning, and the winner is
persisted for auto-apply at bind.  The number that matters is the
step-time of the searched config against the all-defaults baseline —
plus how many trials the cost-model-seeded successive-halving search
spent to find it.

Runs a full `mx.tune.tune()` session over this file's own ``--bench``
child mode (a small MLP train step; fwd+bwd+update, median-of-windows
timing) and reports the winner.  On the CPU CI image the spread
between knob settings is modest — the seed exists to track that the
LOOP stays sound and cheap; on TPU hardware the same harness measures
real donation/batching wins.

Emits ONE JSON line (driver contract):
  {"metric": "tuned_step_time_us", "value": <best>, "unit": "us",
   "vs_baseline": <default-config step time>,
   "extra": {"config": ..., "improved": ..., "trials": ...,
             "search_wall_s": ...}}

Env knobs: MXTPU_BENCH_TUNE_KNOBS ("donate,passes,steps_per_program"),
MXTPU_BENCH_TUNE_TRIALS (6), MXTPU_BENCH_TUNE_STEPS (12),
MXTPU_BENCH_TUNE_HIDDEN (64), MXTPU_BENCH_TUNE_BATCH (32).
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

KNOBS = os.environ.get("MXTPU_BENCH_TUNE_KNOBS",
                       "donate,passes,steps_per_program").split(",")
TRIALS = int(os.environ.get("MXTPU_BENCH_TUNE_TRIALS", "6"))
STEPS = int(os.environ.get("MXTPU_BENCH_TUNE_STEPS", "12"))
HIDDEN = int(os.environ.get("MXTPU_BENCH_TUNE_HIDDEN", "64"))
BATCH = int(os.environ.get("MXTPU_BENCH_TUNE_BATCH", "32"))
FEAT = 32


def _model():
    from mxtpu import sym

    x = sym.Variable("data")
    h = sym.FullyConnected(data=x, num_hidden=HIDDEN, name="fc1")
    h = sym.Activation(data=h, act_type="relu", name="r1")
    h = sym.FullyConnected(data=h, num_hidden=HIDDEN, name="fc2")
    h = sym.Activation(data=h, act_type="relu", name="r2")
    h = sym.FullyConnected(data=h, num_hidden=10, name="fc3")
    return sym.SoftmaxOutput(data=h, label=sym.Variable(
        "softmax_label"), name="softmax")


def mode_bench():
    """Trial body the TrialRunner forks: measure the train step under
    whatever knob env the runner injected, emit the bench row."""
    import numpy as np

    import jax

    import bench_common

    import mxtpu as mx
    from mxtpu.io.io import DataBatch

    mod = mx.mod.Module(_model(), data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (BATCH, FEAT))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01})
    rng = np.random.RandomState(0)
    batch = DataBatch(
        data=[mx.nd.array(rng.rand(BATCH, FEAT).astype("float32"))],
        label=[mx.nd.array(rng.randint(0, 10, BATCH).astype("float32"))])

    def step():
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()

    def sync():
        jax.block_until_ready(
            [a._data for a in mod._exec_group.execs[0].arg_arrays])

    for _ in range(max(3, STEPS // 2)):
        step()
    sync()
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            step()
        sync()
        windows.append((time.perf_counter() - t0) / STEPS * 1e6)
    us = sorted(windows)[1]
    bench_common.emit_result(
        "bench_tune", "mlp_train_step_time_us", round(us, 1), "us",
        step_time_us=round(us, 1), extra={"steps": STEPS})
    return 0


def main():
    import bench_common

    import mxtpu as mx

    net = _model()
    profile = mx.tune.profile_of_shapes([("data", (BATCH, FEAT))])
    with tempfile.TemporaryDirectory(prefix="bench_tune_") as tmp:
        run_dir = os.path.join(tmp, "runs")
        db_dir = os.path.join(tmp, "db")
        t0 = time.perf_counter()
        res = mx.tune.tune(
            [sys.executable, os.path.abspath(__file__), "--bench"],
            symbol=net, profile=profile, knob_names=KNOBS,
            max_trials=TRIALS, run_dir=run_dir, db_dir=db_dir, seed=0)
        wall = time.perf_counter() - t0
    failed = [t.trial_id for t in res.trials if not t.ok]
    for t in res.trials:
        print("%s: rc=%d %s -> %s"
              % (t.trial_id, t.returncode, t.config,
                 "%.1f us" % t.score if t.ok else "failed"),
              file=sys.stderr)
    print("best %s: %.1f us vs baseline %.1f us (improved=%s, "
          "%d trials in %.1f s)"
          % (res.config, res.score, res.baseline_score, res.improved,
             len(res.trials), wall), file=sys.stderr)
    bench_common.emit_result(
        "bench_tune", "tuned_step_time_us", round(res.score, 1), "us",
        vs_baseline=round(res.baseline_score, 1),
        step_time_us=round(res.score, 1),
        extra={"config": res.config, "improved": res.improved,
               "trials": len(res.trials), "failed_trials": failed,
               "knobs": KNOBS, "search_wall_s": round(wall, 1)})
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(mode_bench() if "--bench" in sys.argv[1:] else main())
