"""Graph-rewrite pass pipeline benchmark: bind/trace cost + graph size.

Measures what `mxtpu.passes` (MXTPU_PASSES) buys at COMPILE time on
the two flagship graph families:

  * **resnet** — a gluon model-zoo conv net traced to its Symbol and
    bound through Executor; with ``MXTPU_LAYOUT=nhwc`` the layout pass
    additionally reports the graph-level transpose delta vs the
    per-op ``MXTPU_CONV_LAYOUT`` form (lowered-StableHLO histogram).
  * **transformer** — a symbol-level encoder block stack (QKV
    projections, batch_dot attention, LayerNorm, GELU-ish elementwise
    chains) — CSE/fusion-heavy territory.

For each model, passes OFF vs ON (default set):

  - bind+trace wall time (graph build through jit lower+compile of
    the inference program; the pass pipeline itself is included in
    the ON time, so the number is honest end-to-end)
  - symbol node count before/after
  - compiled-program fusion count (optimized-HLO histogram)

Emits ONE JSON line (driver contract):
  {"metric": "passes_bind_speedup", "value": <x>, "unit": "x",
   "vs_baseline": <x>, "extra": {...}}
("baseline" is passes-off, so vs_baseline == value; a value ~1.0 with
large node reductions means the pipeline pays for itself at bind while
shrinking what every later retrace has to walk.)

Env knobs: MXTPU_BENCH_PASSES_NET (resnet18_v1), MXTPU_BENCH_PASSES_HW
(32), MXTPU_BENCH_PASSES_BATCH (2), MXTPU_BENCH_PASSES_LAYERS (2,
transformer depth), MXTPU_BENCH_PASSES_DMODEL (64).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

NET = os.environ.get("MXTPU_BENCH_PASSES_NET", "resnet18_v1")
HW = int(os.environ.get("MXTPU_BENCH_PASSES_HW", "32"))
BATCH = int(os.environ.get("MXTPU_BENCH_PASSES_BATCH", "2"))
LAYERS = int(os.environ.get("MXTPU_BENCH_PASSES_LAYERS", "2"))
DMODEL = int(os.environ.get("MXTPU_BENCH_PASSES_DMODEL", "64"))
SEQ = int(os.environ.get("MXTPU_BENCH_PASSES_SEQ", "32"))


def _resnet_symbol():
    import mxtpu as mx
    from mxtpu import sym
    from mxtpu.gluon.model_zoo import vision

    net = vision.get_model(NET, classes=10)
    net.initialize(ctx=mx.cpu())
    x = mx.nd.zeros((BATCH, 3, HW, HW))
    out_sym, _, _ = net._trace_symbol(x)
    data_name = out_sym.list_arguments()[0]  # trace names it data0
    return out_sym, {data_name: (BATCH, 3, HW, HW)}


def _transformer_symbol():
    """Symbol-level encoder stack: the pass-pipeline stress shape
    (duplicate projections for CSE, long elementwise chains for fuse,
    scale constants for fold)."""
    from mxtpu import sym

    d, h = DMODEL, 4
    x = sym.Variable("data")  # (B, T, d)
    cur = x
    for i in range(LAYERS):
        p = "l%d_" % i
        q = sym.FullyConnected(data=cur, num_hidden=d, flatten=False,
                               name=p + "q")
        k = sym.FullyConnected(data=cur, num_hidden=d, flatten=False,
                               name=p + "k")
        v = sym.FullyConnected(data=cur, num_hidden=d, flatten=False,
                               name=p + "v")
        att = sym.batch_dot(q, sym.SwapAxis(k, dim1=1, dim2=2),
                            name=p + "qk")
        att = sym.softmax(att * (1.0 / float(d // h) ** 0.5),
                          axis=-1)
        ctx_ = sym.batch_dot(att, v, name=p + "av")
        proj = sym.FullyConnected(data=ctx_, num_hidden=d, flatten=False,
                                  name=p + "proj")
        cur = sym.LayerNorm(data=cur + proj, name=p + "ln1")
        ff = sym.FullyConnected(data=cur, num_hidden=4 * d, flatten=False,
                                name=p + "ff1")
        # gelu-ish elementwise chain (tanh approximation): fuse fodder
        ff = 0.5 * ff * (1.0 + sym.tanh(
            0.7978845608 * (ff + 0.044715 * ff * ff * ff)))
        ff = sym.FullyConnected(data=ff, num_hidden=d, flatten=False,
                                name=p + "ff2")
        cur = sym.LayerNorm(data=cur + ff, name=p + "ln2")
    return cur, {"data": (BATCH, SEQ, d)}


def _bind_once(symbol, shapes, spec):
    """Bind + force the inference compile; returns (wall_s, executor)."""
    import numpy as np

    import mxtpu as mx
    import mxtpu.passes as P

    t0 = time.perf_counter()
    with P.scope(spec):
        ex = symbol.simple_bind(mx.cpu(), grad_req="null", **shapes)
    ex.forward(**{n: mx.nd.array(np.zeros(s, "float32"))
                  for n, s in shapes.items()})
    return time.perf_counter() - t0, ex


def _fusions(ex):
    import mxtpu as mx

    try:
        si = ex._insp.latest_sig()
        return mx.inspect.hlo_histogram(si.hlo_text()).get("n_fusions")
    except Exception:
        return None


def bench_model(tag, build):
    import mxtpu.passes as P

    symbol, shapes = build()
    _, report = symbol.optimize(passes="default", return_report=True)
    _bind_once(symbol, shapes, "off")  # warmup: jax/XLA cold-start out
    t_off, ex_off = _bind_once(symbol, shapes, "off")
    t_on, ex_on = _bind_once(symbol, shapes, "default")
    row = {
        "model": tag,
        "bind_s_off": round(t_off, 3),
        "bind_s_on": round(t_on, 3),
        "bind_speedup": round(t_off / t_on, 3) if t_on else None,
        "nodes_before": report["nodes_before"],
        "nodes_after": report["nodes_after"],
        "per_pass": {p["pass"]: {k: v for k, v in p.items()
                                 if k in ("wall_us", "identity_removed",
                                          "folded", "cse_merged",
                                          "chains", "nodes_fused")}
                     for p in report["passes"]},
        "fusions_off": _fusions(ex_off),
        "fusions_on": _fusions(ex_on),
    }
    return row


def main():
    rows = [bench_model("resnet", _resnet_symbol),
            bench_model("transformer", _transformer_symbol)]
    speedups = [r["bind_speedup"] for r in rows if r["bind_speedup"]]
    value = round(sum(speedups) / len(speedups), 3) if speedups else 0.0
    import bench_common

    bench_common.emit_result(
        "passes", "passes_bind_speedup", value, "x",
        extra={"models": rows,
               "net": NET, "hw": HW, "batch": BATCH,
               "node_reduction": {
                   r["model"]: "%d->%d" % (r["nodes_before"],
                                           r["nodes_after"])
                   for r in rows}})


if __name__ == "__main__":
    main()
