"""Compile-lifecycle benchmark: cold vs warm bind, ragged-batch serving.

Measures the three levers of `mxtpu/compile_cache.py` on a gluon
model-zoo net:

  * **cold vs warm start** — a subprocess binds + warms up resnet18_v1
    through Module/Executor with `MXTPU_COMPILE_CACHE` pointed at a
    fresh directory (cold: full XLA compile) and then again with the
    now-populated cache (warm: disk deserialization).  The headline
    metric is the warm-start speedup of the bind+warmup phase.

  * **ragged-batch inference** — batch sizes cycling over 1..MAX served
    through a hybridized net with shape bucketing OFF (one compiled
    program per distinct size) vs ON (<= log2 bucket programs), reporting
    wall time and program counts for each.

Emits ONE structured row via `bench_common.emit_result` (the shared
runner schema every seed and the `tools/check_perf.py` ratchet read);
metric "compile_cache_warm_bind_speedup", "baseline" is the cold
start, so vs_baseline == value.

Env knobs: MXTPU_BENCH_CC_NET (default resnet18_v1),
MXTPU_BENCH_CC_BATCH (default 4), MXTPU_BENCH_CC_HW (input H=W,
default 64 — resnet is global-pooled, so small inputs keep the CPU
fallback fast), MXTPU_BENCH_CC_MAXB (ragged sweep upper bound, 8).
"""
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

NET = os.environ.get("MXTPU_BENCH_CC_NET", "resnet18_v1")
BATCH = int(os.environ.get("MXTPU_BENCH_CC_BATCH", "4"))
HW = int(os.environ.get("MXTPU_BENCH_CC_HW", "64"))
MAXB = int(os.environ.get("MXTPU_BENCH_CC_MAXB", "8"))

_BIND_SCRIPT = r"""
import os, sys, time
cache_dir = sys.argv[1]
os.environ["MXTPU_COMPILE_CACHE"] = cache_dir
import numpy as np
t0 = time.perf_counter()
import mxtpu as mx
from mxtpu.gluon.model_zoo import vision
net = getattr(vision, %(net)r)(classes=10)
net.initialize(ctx=mx.cpu())
net.hybridize()
t_import = time.perf_counter() - t0
t1 = time.perf_counter()
net.warmup([(%(batch)d, 3, %(hw)d, %(hw)d)])
t_warmup = time.perf_counter() - t1
# one real batch through the warmed executable (no compile)
t2 = time.perf_counter()
out = net(mx.nd.array(np.ones((%(batch)d, 3, %(hw)d, %(hw)d), "float32")))
out.wait_to_read()
t_first = time.perf_counter() - t2
assert net._cached_op._jit_infer._cache_size() == 0
print("BIND_JSON " + __import__("json").dumps(
    {"import_s": t_import, "warmup_s": t_warmup, "first_batch_s": t_first}))
"""


def _run_bind(cache_dir):
    code = _BIND_SCRIPT % {"net": NET, "batch": BATCH, "hw": HW}
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code, cache_dir],
                       capture_output=True, text=True, timeout=1200,
                       env=env, cwd=REPO)
    if r.returncode != 0:
        raise RuntimeError("bind subprocess failed: %s" % r.stderr[-2000:])
    for line in r.stdout.splitlines():
        if line.startswith("BIND_JSON "):
            return json.loads(line[len("BIND_JSON "):])
    raise RuntimeError("no BIND_JSON line in output")


def bench_cold_warm():
    with tempfile.TemporaryDirectory() as d:
        cache = os.path.join(d, "xla")
        cold = _run_bind(cache)
        warm = _run_bind(cache)
    return cold, warm


def bench_ragged():
    import numpy as np

    import mxtpu as mx
    from mxtpu.gluon.model_zoo import vision

    results = {}
    batches = [np.random.RandomState(b).rand(b, 3, HW, HW).astype("float32")
               for b in range(1, MAXB + 1)]
    for mode, policy in (("off", None), ("pow2", "pow2")):
        mx.set_bucket_policy(policy or "off")
        net = getattr(vision, NET)(classes=10)
        net.initialize(ctx=mx.cpu())
        net.hybridize()
        net(mx.nd.array(batches[-1])).wait_to_read()  # trace once at MAXB
        t0 = time.perf_counter()
        for arr in batches:
            net(mx.nd.array(arr)).wait_to_read()
        dt = time.perf_counter() - t0
        results[mode] = {
            "sweep_s": round(dt, 3),
            "programs": net._cached_op._jit_infer._cache_size(),
            "imgs_per_sec": round(sum(a.shape[0] for a in batches) / dt, 2),
        }
    mx.set_bucket_policy(None)
    return results


def main():
    extra = {"net": NET, "batch": BATCH, "hw": HW,
             "platform": "cpu" if os.environ.get("JAX_PLATFORMS") == "cpu"
             else os.environ.get("JAX_PLATFORMS", "auto")}
    cold, warm = bench_cold_warm()
    extra["cold_warmup_s"] = round(cold["warmup_s"], 3)
    extra["warm_warmup_s"] = round(warm["warmup_s"], 3)
    extra["cold_first_batch_s"] = round(cold["first_batch_s"], 4)
    extra["warm_first_batch_s"] = round(warm["first_batch_s"], 4)
    speedup = cold["warmup_s"] / max(warm["warmup_s"], 1e-9)
    try:
        extra["ragged"] = bench_ragged()
    except Exception as e:  # ragged sweep must not sink the record
        extra["ragged_error"] = str(e)[:300]
    import bench_common

    bench_common.emit_result(
        "compile_cache", "compile_cache_warm_bind_speedup",
        round(speedup, 2), "x",
        step_time_us=round(warm["warmup_s"] * 1e6, 1),
        extra=extra)


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
