"""Sparse op micro-benchmarks (reference
`benchmark/python/sparse/sparse_end2end.py`): row-sparse embedding
gradient vs dense at growing vocab — the wire/compute win sparse exists
for.

Usage: python benchmark/python/bench_sparse.py [--vocabs 10000,100000]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import autograd, nd


def bench(vocab, dim, batch, iters, sparse):
    rng = np.random.RandomState(0)
    w = nd.array(rng.uniform(-1, 1, (vocab, dim)).astype(np.float32))
    if sparse:
        gbuf = mx.nd.sparse.zeros("row_sparse", (vocab, dim))
        mx.autograd.mark_variables([w], [gbuf])
    else:
        w.attach_grad()
    ids = nd.array(rng.randint(0, vocab, (batch, 16)).astype(np.float32))

    def step():
        with autograd.record():
            e = nd.Embedding(ids, w, input_dim=vocab, output_dim=dim,
                             sparse_grad=sparse)
            loss = (e * e).sum()
        loss.backward()
        return w.grad

    g = step()
    (g.tostype("default") if sparse else g).wait_to_read()
    tic = time.perf_counter()
    for _ in range(iters):
        g = step()
    (g.tostype("default") if sparse else g).wait_to_read()
    return iters / (time.perf_counter() - tic)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocabs", default="10000,100000,1000000")
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()
    for vocab in (int(v) for v in args.vocabs.split(",")):
        d = bench(vocab, args.dim, args.batch, args.iters, False)
        s = bench(vocab, args.dim, args.batch, args.iters, True)
        print("vocab=%-8d dense %8.1f steps/s   row_sparse %8.1f "
              "steps/s   speedup %.2fx" % (vocab, d, s, s / d))


if __name__ == "__main__":
    main()
