"""Sparse op micro-benchmarks (reference
`benchmark/python/sparse/sparse_end2end.py`): row-sparse embedding
gradient vs dense at growing vocab — the wire/compute win sparse exists
for.

Usage: python benchmark/python/bench_sparse.py [--vocabs 10000,100000]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import autograd, nd


def bench(vocab, dim, batch, iters, sparse):
    rng = np.random.RandomState(0)
    w = nd.array(rng.uniform(-1, 1, (vocab, dim)).astype(np.float32))
    if sparse:
        gbuf = mx.nd.sparse.zeros("row_sparse", (vocab, dim))
        mx.autograd.mark_variables([w], [gbuf])
    else:
        w.attach_grad()
    ids = nd.array(rng.randint(0, vocab, (batch, 16)).astype(np.float32))

    def step():
        with autograd.record():
            e = nd.Embedding(ids, w, input_dim=vocab, output_dim=dim,
                             sparse_grad=sparse)
            loss = (e * e).sum()
        loss.backward()
        return w.grad

    g = step()
    (g.tostype("default") if sparse else g).wait_to_read()
    tic = time.perf_counter()
    for _ in range(iters):
        g = step()
    (g.tostype("default") if sparse else g).wait_to_read()
    return iters / (time.perf_counter() - tic)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocabs", default="10000,100000,1000000")
    p.add_argument("--dim", type=int, default=64)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--iters", type=int, default=20)
    args = p.parse_args()
    rows = {}
    for vocab in (int(v) for v in args.vocabs.split(",")):
        d = bench(vocab, args.dim, args.batch, args.iters, False)
        s = bench(vocab, args.dim, args.batch, args.iters, True)
        rows["vocab%d" % vocab] = {
            "dense_steps_per_s": round(d, 1),
            "row_sparse_steps_per_s": round(s, 1),
            "speedup": round(s / d, 3)}
        print("vocab=%-8d dense %8.1f steps/s   row_sparse %8.1f "
              "steps/s   speedup %.2fx" % (vocab, d, s, s / d),
              file=sys.stderr)
    # structured row (shared runner schema): the headline is the
    # speedup at the LARGEST vocab — where sparse exists to win
    import bench_common

    last = list(rows.values())[-1] if rows else {}
    bench_common.emit_result(
        "sparse", "row_sparse_embedding_speedup",
        last.get("speedup", 0.0), "x",
        throughput=last.get("row_sparse_steps_per_s"),
        step_time_us=(1e6 / last["row_sparse_steps_per_s"])
        if last.get("row_sparse_steps_per_s") else None,
        extra={"dim": args.dim, "batch": args.batch,
               "iters": args.iters, "rows": rows})


if __name__ == "__main__":
    main()
