"""ZeRO-1 sharding benchmark seed: optimizer-state bytes + step wall
time vs replica count.

What `mx.shard` buys (ROADMAP item 1): per-replica optimizer-state
memory drops ~1/N while the training trajectory stays bitwise — so the
metric that matters is state bytes/replica against the step-time cost
of the slice-update + allgather on this host.  Runs the host-replica
ZeRO-1 engine (Module over N cpu contexts, Adam) for each replica
count in MXTPU_BENCH_SHARD_REPLICAS, replicated vs sharded, and — on
a multi-device mesh — the FusedTrainLoop GSPMD sharded-carry variant.

On the virtual CPU mesh the step-time numbers are code-path overhead
(no real interconnect); on TPU hardware the same harness reports the
ICI-bound allgather cost against the HBM freed per chip.

Emits ONE JSON line (driver contract):
  {"metric": "zero1_state_fraction", "value": <per-replica/full at max
   N>, "unit": "x", "vs_baseline": <sharded/replicated step time>,
   "extra": {per-N rows}}

Env knobs: MXTPU_BENCH_SHARD_REPLICAS ("1,2,4"), MXTPU_BENCH_SHARD_STEPS
(30), MXTPU_BENCH_SHARD_HIDDEN (256), MXTPU_BENCH_SHARD_BATCH (64).
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()

REPLICAS = [int(x) for x in os.environ.get(
    "MXTPU_BENCH_SHARD_REPLICAS", "1,2,4").split(",")]
STEPS = int(os.environ.get("MXTPU_BENCH_SHARD_STEPS", "30"))
HIDDEN = int(os.environ.get("MXTPU_BENCH_SHARD_HIDDEN", "256"))
BATCH = int(os.environ.get("MXTPU_BENCH_SHARD_BATCH", "64"))
FEAT = 64


def _model():
    from mxtpu import sym

    x = sym.Variable("data")
    h = sym.FullyConnected(data=x, num_hidden=HIDDEN, name="fc1")
    h = sym.Activation(data=h, act_type="relu", name="r1")
    h = sym.FullyConnected(data=h, num_hidden=HIDDEN, name="fc2")
    h = sym.Activation(data=h, act_type="relu", name="r2")
    h = sym.FullyConnected(data=h, num_hidden=8, name="fc3")
    return sym.SoftmaxOutput(data=h, label=sym.Variable("softmax_label"),
                             name="softmax")


def _run(mx, np, plan, n_ctx):
    """Train STEPS batches; returns (s/step, per_replica_state_bytes,
    full_state_bytes)."""
    import contextlib

    from mxtpu.io.io import DataBatch
    from mxtpu.sharding import ZeRO1Updater, zero1 as z1

    rng = np.random.RandomState(0)
    batches = [DataBatch(
        data=[mx.nd.array(rng.rand(BATCH, FEAT).astype("float32"))],
        label=[mx.nd.array(rng.randint(0, 8, BATCH).astype("float32"))])
        for _ in range(STEPS)]
    scope = plan.activate() if plan is not None \
        else contextlib.nullcontext()
    with scope:
        mod = mx.mod.Module(_model(),
                            context=[mx.cpu(i) for i in range(n_ctx)])
        mod.bind(data_shapes=[("data", (BATCH, FEAT))],
                 label_shapes=[("softmax_label", (BATCH,))])
        mx.random.seed(1)
        mod.init_params(initializer=mx.init.Xavier())
        mod.init_optimizer(kvstore="device", optimizer="adam",
                           optimizer_params={"learning_rate": 0.01})
        for b in batches[:3]:   # warm compiles out of the timing
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
        t0 = time.perf_counter()
        for b in batches[3:]:
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()
        mod.get_params()        # sync
        dt = (time.perf_counter() - t0) / max(1, STEPS - 3)
        upd = mod._updater
        if isinstance(upd, ZeRO1Updater):
            full = z1.tree_nbytes(upd._gather_full())
            per = upd.per_replica_state_nbytes()
        else:
            # replicated: with update_on_kvstore the ONE kvstore-side
            # state stands in for what EACH replica would hold under
            # per-device updaters
            import pickle

            states = mod._kvstore._updater.get_states() \
                if mod._update_on_kvstore else upd.get_states()
            full = per = z1.tree_nbytes(pickle.loads(states)[0])
        return dt, per, full


def main():
    import numpy as np

    import jax

    import mxtpu as mx
    from mxtpu.sharding import ShardingPlan

    rows = {}
    frac = 1.0
    ratio = 1.0
    for n in REPLICAS:
        if jax.device_count() < n:
            continue
        t_rep, per_rep, _ = _run(mx, np, None, n)
        t_sh, per_sh, full = _run(
            mx, np, ShardingPlan(min_shard_elems=1024), n)
        frac = per_sh / float(full)
        ratio = t_sh / t_rep if t_rep > 0 else 1.0
        rows["n%d" % n] = {
            "replicated_ms_step": round(t_rep * 1e3, 3),
            "sharded_ms_step": round(t_sh * 1e3, 3),
            "state_bytes_per_replica": per_sh,
            "state_bytes_full": full,
            "state_fraction": round(frac, 4),
        }
        print("n=%d: %.2f -> %.2f ms/step, state %.1f -> %.1f KiB "
              "per replica (%.3f of full)"
              % (n, t_rep * 1e3, t_sh * 1e3, per_rep / 1024.0,
                 per_sh / 1024.0, frac), file=sys.stderr)
    import bench_common

    last = list(rows.values())[-1] if rows else {}
    bench_common.emit_result(
        "sharding", "zero1_state_fraction", round(frac, 4), "x",
        vs_baseline=round(ratio, 3),
        step_time_us=last.get("sharded_ms_step", 0) * 1e3 or None,
        extra=rows)
    return 0


if __name__ == "__main__":
    sys.exit(main())
