"""Shared structured-result runner for the `bench_*.py` seeds.

Every benchmark in this directory emits its headline result through
:func:`emit_result`, so all seeds — and `tools/check_perf.py`'s
ratchet micro-benches, and the future `mx.tune` trial harness — speak
ONE row schema::

    {"schema": "mxtpu-bench-v1",
     "bench": "serving",                  # which seed produced it
     "metric": "...", "value": <float>, "unit": "...",
     "vs_baseline": <float|null>,        # legacy driver contract keys
     "throughput": <rows-or-steps/s|null>,
     "step_time_us": <float|null>,
     "mfu": <float|null>,                # from metrics()["perf"], when
     "phases": {...}|null,               # the run had an observatory
     "knobs": {"MXTPU_*": ..., "JAX_PLATFORMS": ...},
     "extra": {...}}                     # free-form per-bench detail

The row is printed as the LAST stdout line (the driver takes the final
JSON line — strict superset of the old ``{metric,value,unit,
vs_baseline,extra}`` contract, so existing consumers keep parsing) and
appended to ``$MXTPU_BENCH_OUT`` as JSONL when set, which is how the
perf-regression ratchet (`tools/check_perf.py`,
``benchmark/baselines/<backend>.json``) and any sweep driver collect
rows without scraping human output.

Seeds should call :func:`emit_result` exactly once, at the end, after
their measurement loops — the MFU/phase columns are read from the
LIVE `mx.perf` observatory at emit time.
"""
import json
import os
from typing import Any, Dict, Optional

SCHEMA = "mxtpu-bench-v1"

#: env keys that parameterize performance — recorded on every row so a
#: regression can be traced to a knob flip, and so `mx.tune` trials
#: are reproducible from their rows alone
_KNOB_PREFIXES = ("MXTPU_",)
_KNOB_EXTRA = ("JAX_PLATFORMS", "XLA_FLAGS")


def knobs() -> Dict[str, str]:
    """The performance-relevant environment at emit time."""
    out = {}
    for k, v in sorted(os.environ.items()):
        if k.startswith(_KNOB_PREFIXES) or k in _KNOB_EXTRA:
            out[k] = v
    return out


def perf_summary() -> Optional[Dict[str, Any]]:
    """The live `mx.perf` view (mfu / dominant phase / per-step phase
    averages), or None when the framework was never imported or the
    observatory is off."""
    try:
        import sys

        mx = sys.modules.get("mxtpu")
        if mx is None:
            return None
        blk = mx.telemetry.metrics().get("perf")
        if not blk or not blk.get("enabled"):
            return None
        return {"mfu": blk.get("mfu"),
                "dominant_phase": blk.get("dominant_phase"),
                "phases_us_per_step": blk.get("phases_us_per_step")}
    except Exception:
        return None


def op_profile_summary() -> Optional[Dict[str, Any]]:
    """The live `mx.xprof` per-op breakdown (compact: per-class us +
    top sinks), or None when nothing was profiled this process.  Rows
    carry it when the seed ran under ``--profile`` (or called
    ``mx.xprof.profile``/``ingest`` itself) — the data
    ``tools/compare_runs.py`` uses to answer WHICH op got slower."""
    try:
        import sys

        mx = sys.modules.get("mxtpu")
        if mx is None:
            return None
        return mx.xprof.bench_breakdown()
    except Exception:
        return None


def hbm_summary() -> Optional[Dict[str, Any]]:
    """The live `mx.hbm` device-memory view: process peak-used bytes
    plus the per-class plan of the biggest registered program (the
    data ``tools/compare_runs.py`` uses to answer WHICH memory class
    grew).  None when the framework was never imported or the census
    is off."""
    try:
        import sys

        mx = sys.modules.get("mxtpu")
        if mx is None or not mx.hbm.enabled():
            return None
        c = mx.hbm.census()
        if not c.get("enabled"):
            return None
        out = {"peak_hbm_bytes": c.get("peak_used_bytes", 0),
               "used_bytes": c.get("used_bytes", 0),
               "headroom_bytes": c.get("headroom_bytes", 0)}
        plans = mx.hbm.report(top=1).get("plans") or []
        if plans:
            out["plan"] = {"program": plans[0].get("program"),
                           "peak_bytes": plans[0].get("peak_bytes"),
                           "classes": plans[0].get("classes")}
        return out
    except Exception:
        return None


def row(bench: str, metric: str, value: float, unit: str,
        vs_baseline: Optional[float] = None,
        throughput: Optional[float] = None,
        step_time_us: Optional[float] = None,
        mfu: Optional[float] = None,
        phases: Optional[Dict[str, Any]] = None,
        op_profile: Optional[Dict[str, Any]] = None,
        hbm: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Build one structured result row (see module doc for schema).
    ``mfu``/``phases`` default to the live `mx.perf` observatory;
    ``op_profile`` defaults to the live `mx.xprof` breakdown when one
    exists; ``hbm`` defaults to the live `mx.hbm` census + top plan
    (superset keys — absent on runs without them)."""
    p = perf_summary()
    if p is not None:
        if mfu is None:
            mfu = p.get("mfu")
        if phases is None:
            phases = p.get("phases_us_per_step")
    if op_profile is None:
        op_profile = op_profile_summary()
    if hbm is None:
        hbm = hbm_summary()
    # an `mx.tune` trial subprocess stamps its trial id into the row
    # so ledger rows are attributable to the trial that produced them
    trial = os.environ.get("MXTPU_TUNE_TRIAL")
    if trial:
        extra = dict(extra or {})
        extra.setdefault("tune_trial", trial)
    return {
        "schema": SCHEMA,
        "bench": bench,
        "metric": metric,
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline if vs_baseline is not None else value,
        "throughput": throughput,
        "step_time_us": step_time_us,
        "mfu": mfu,
        "phases": phases,
        "knobs": knobs(),
        "extra": extra or {},
        **({"op_profile": op_profile} if op_profile else {}),
        **({"peak_hbm_bytes": hbm.get("peak_hbm_bytes"),
            "hbm_plan": hbm.get("plan")} if hbm else {}),
    }


def emit(r: Dict[str, Any]) -> Dict[str, Any]:
    """Print ``r`` as the result line and append it to
    ``$MXTPU_BENCH_OUT`` (JSONL) when set.  With ``MXTPU_RUN_DIR``
    set, the row also lands in the `mx.obs` run ledger
    (``<run_id>.jsonl``, ``kind="bench"``) — the trial-history rows
    ``tools/compare_runs.py`` diffs and `mx.tune` will search.
    Returns ``r``."""
    line = json.dumps(r, default=str)
    print(line)
    path = os.environ.get("MXTPU_BENCH_OUT")
    if path:
        try:
            with open(path, "a") as f:
                f.write(line + "\n")
        except OSError:
            pass  # a broken sink must not fail the bench
    if os.environ.get("MXTPU_RUN_DIR"):
        try:
            import sys
            import time

            mx = sys.modules.get("mxtpu")
            if mx is not None:
                row_ = dict(r)
                row_.setdefault("kind", "bench")
                row_.setdefault("ts", time.time())
                mx.obs.ledger_append(row_)
        except Exception:
            pass  # the ledger must not fail the bench either
    return r


def emit_result(bench: str, metric: str, value: float, unit: str,
                **kwargs) -> Dict[str, Any]:
    """:func:`row` + :func:`emit` in one call — the one-liner every
    ``bench_*.py`` seed ends with."""
    return emit(row(bench, metric, value, unit, **kwargs))
