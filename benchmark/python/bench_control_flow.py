"""Control-flow op micro-benchmarks (reference
`benchmark/python/control_flow/`): foreach (lax.scan) vs a python
unrolled loop at growing sequence length — the compile-once win.

Usage: python benchmark/python/bench_control_flow.py [--lengths 32,128]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxtpu as mx
from mxtpu import nd


def bench_foreach(T, hidden, iters):
    x = nd.array(np.random.RandomState(0).rand(T, 32, hidden)
                 .astype(np.float32))
    h0 = nd.zeros((32, hidden))
    w = nd.array(np.random.RandomState(1).rand(hidden, hidden)
                 .astype(np.float32) * 0.01)

    def body(inp, state):
        nh = nd.tanh(nd.dot(inp, w) + state[0])
        return nh, [nh]

    out, _ = mx.nd.contrib.foreach(body, x, [h0])
    out.wait_to_read()
    tic = time.perf_counter()
    for _ in range(iters):
        out, _ = mx.nd.contrib.foreach(body, x, [h0])
    out.wait_to_read()
    return iters / (time.perf_counter() - tic)


def bench_unrolled(T, hidden, iters):
    x = nd.array(np.random.RandomState(0).rand(T, 32, hidden)
                 .astype(np.float32))
    w = nd.array(np.random.RandomState(1).rand(hidden, hidden)
                 .astype(np.float32) * 0.01)

    def run():
        h = nd.zeros((32, hidden))
        for t in range(T):
            h = nd.tanh(nd.dot(x[t], w) + h)
        return h

    run().wait_to_read()
    tic = time.perf_counter()
    for _ in range(iters):
        out = run()
    out.wait_to_read()
    return iters / (time.perf_counter() - tic)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--lengths", default="32,128")
    p.add_argument("--hidden", type=int, default=128)
    p.add_argument("--iters", type=int, default=10)
    args = p.parse_args()
    for T in (int(t) for t in args.lengths.split(",")):
        f = bench_foreach(T, args.hidden, args.iters)
        u = bench_unrolled(T, args.hidden, args.iters)
        print("T=%-4d foreach(scan) %8.2f it/s   unrolled %8.2f it/s   "
              "speedup %.1fx" % (T, f, u, f / u))


if __name__ == "__main__":
    main()
