#!/usr/bin/env python
"""Long-context TransformerLM training: flash (Pallas) vs jnp attention.

The per-kernel sweep (`RESULTS_attention.md`) shows the flash kernel's
margin growing with T; this benchmark measures the same effect at the
FULL TRAINING STEP level — `mxtpu.parallel.transformer.make_train_step`
(fwd+bwd+Adam) at fixed tokens-per-batch while the sequence length
grows, with the attention path toggled via MXTPU_NO_PALLAS in a child
process (the routing is trace-time-static, so each config gets a fresh
interpreter; the child is this same script with --child, so the timing
loop exists exactly once).

Timing is value-synced (loss + one element of the updated params):
buffer-readiness fences are unreliable through the tunnel after a
pallas execution (BENCH_NOTES_r05.md).  One JSON line per config; a
"flash" row whose child reports the kernel did not actually engage is
marked as an error instead of printing a misleading 0% comparison.

Usage: python benchmark/python/bench_long_context.py [--seqs 1024,2048,4096]
"""
import argparse
import json
import math
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_child(T, tokens, iters, remat):
    """One measured config in THIS process; prints one JSON line."""
    sys.path.insert(0, REPO)
    import time

    import numpy as np
    import jax
    import jax.numpy as jnp

    from mxtpu.parallel import transformer as tf
    from mxtpu.parallel.mesh import (create_mesh, AXIS_DP, AXIS_PP,
                                     AXIS_TP, AXIS_SP, AXIS_EP)
    from mxtpu.ops.pallas_attention import _use_pallas

    B = max(1, tokens // T)
    mesh = create_mesh({AXIS_DP: 1, AXIS_PP: 1, AXIS_TP: 1, AXIS_SP: 1,
                        AXIS_EP: 1}, devices=jax.devices()[:1])
    cfg = tf.TransformerConfig(vocab=8192, d_model=1024, n_heads=8,
                               n_layers=8, d_ff=4096, max_len=T,
                               dtype="bfloat16", remat=remat)
    params = tf.init_params(cfg, mesh, seed=0)
    opt = tf.init_opt_state(cfg, mesh)
    step, sh = tf.make_train_step(cfg, mesh, lr=1e-3, optimizer="adam")
    rng = np.random.RandomState(0)
    toks = jax.device_put(
        rng.randint(0, cfg.vocab, (B, T)).astype(np.int32), sh["data"])
    labs = jax.device_put(
        rng.randint(0, cfg.vocab, (B, T)).astype(np.int32), sh["data"])

    def value_sync(params, loss):
        lv = float(loss)
        float(jnp.ravel(jax.tree_util.tree_leaves(params)[0])[0])
        return lv

    for _ in range(2):
        params, opt, loss = step(params, opt, toks, labs)
    value_sync(params, loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt, loss = step(params, opt, toks, labs)
    lv = value_sync(params, loss)
    dt = time.perf_counter() - t0
    if not math.isfinite(lv):
        raise RuntimeError("loss diverged: %r" % lv)
    print(json.dumps({"T": T, "B": B,
                      "tokens_per_sec": round(B * T * iters / dt, 1),
                      "loss": round(lv, 4), "remat": remat,
                      "pallas": bool(_use_pallas())}))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="1024,2048,4096")
    ap.add_argument("--tokens", type=int, default=8192,
                    help="tokens per batch (B = tokens // T)")
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--remat", default="none",
                    choices=("none", "dots", "dots_no_batch", "full"),
                    help="per-layer rematerialization; 'full' is what "
                         "makes T>=8k fit on one chip")
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-config child timeout (a wedged tunnel "
                         "must not hang the whole sweep)")
    ap.add_argument("--child", type=int, default=None,
                    help=argparse.SUPPRESS)  # internal: run one T
    args = ap.parse_args()

    if args.child is not None:
        run_child(args.child, args.tokens, args.iters, args.remat)
        return

    for t in [int(s) for s in args.seqs.split(",") if s]:
        for no_pallas in ("0", "1"):
            path = "jnp" if no_pallas == "1" else "flash"
            env = dict(os.environ)
            env["MXTPU_NO_PALLAS"] = no_pallas
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--child", str(t), "--tokens", str(args.tokens),
                     "--iters", str(args.iters),
                     "--remat", args.remat],
                    capture_output=True, text=True, env=env,
                    timeout=args.timeout)
            except subprocess.TimeoutExpired:
                print(json.dumps({"T": t, "path": path,
                                  "error": "child timeout (%.0fs)"
                                           % args.timeout}))
                continue
            line = (r.stdout.strip().splitlines() or [""])[-1]
            if r.returncode == 0 and line.startswith("{"):
                rec = json.loads(line)
                rec["path"] = path
                if path == "flash" and not rec.get("pallas"):
                    # both rows would silently measure the jnp path
                    rec = {"T": t, "path": path,
                           "error": "pallas kernel did not engage on "
                                    "this backend; comparison invalid"}
                print(json.dumps(rec))
            else:
                print(json.dumps({"T": t, "path": path,
                                  "error": (r.stderr
                                            or "no output")[-300:]}))


if __name__ == "__main__":
    main()
